"""gRPC ingress for Serve.

Parity target: reference python/ray/serve/_private/proxy.py:530 (gRPCProxy
— a per-node gRPC server routing RPCs to deployment replicas, sharing the
HTTP proxy's route table and router machinery, including server-streaming
responses). The reference serves user-registered proto services; here a
GENERIC handler serves every deployment without protoc: the fully-
qualified method name carries the route —

    /ray_tpu.serve.<deployment>/<method>        unary -> unary
    /ray_tpu.serve.<deployment>/<method>Stream  unary -> server stream

Request/response payloads are raw bytes: callers send whatever the
deployment expects (JSON, pickle, protobuf-encoded messages of their own
schema); the deployment's return value is sent back pickled unless it is
already bytes. Streaming methods ride the same core streaming-generator
transport as the HTTP SSE path.
"""

from __future__ import annotations

import logging
import pickle
from concurrent import futures as _futures
from typing import Optional

logger = logging.getLogger(__name__)

_PREFIX = "ray_tpu.serve."


class _GrpcRequest:
    """Request view handed to deployments for gRPC ingress (the role the
    reference fills with the user proto message + grpc_context)."""

    def __init__(self, method: str, body: bytes, metadata: dict):
        self.method = "GRPC"
        self.grpc_method = method
        self.body = body
        self.headers = metadata
        self.path = method
        self.query = {}

    def json(self):
        import json as _json

        return _json.loads(self.body or b"null")

    def __repr__(self):
        return f"GrpcRequest({self.grpc_method})"


def _encode(value) -> bytes:
    if isinstance(value, bytes):
        return value
    return pickle.dumps(value)


class GrpcIngress:
    """Generic gRPC server bound inside the proxy actor. Routes by method
    name; deployment lookup + replica routing reuse the proxy's router."""

    def __init__(self, proxy, host: str, port: int):
        import grpc

        self._proxy = proxy
        self._grpc = grpc
        self._server = grpc.server(
            _futures.ThreadPoolExecutor(
                max_workers=32, thread_name_prefix="rt-grpc"),
            options=[("grpc.so_reuseport", 0)])
        self._server.add_generic_rpc_handlers((_Handler(self),))
        self.port = self._server.add_insecure_port(f"{host}:{port}")
        if self.port == 0:
            # grpc returns 0 on bind failure instead of raising; a silently
            # dead ingress would report "enabled" while refusing everything.
            raise OSError(f"gRPC ingress could not bind {host}:{port}")
        self._server.start()

    def stop(self):
        self._server.stop(grace=1.0)

    # ------------------------------------------------------------- routing
    def _route(self, full_method: str):
        """'/ray_tpu.serve.<dep>/<method>' -> (deployment, method, stream)."""
        try:
            service, method = full_method.lstrip("/").split("/", 1)
        except ValueError:
            return None
        if not service.startswith(_PREFIX):
            return None
        dep = service[len(_PREFIX):]
        stream = method.endswith("Stream")
        if stream:
            method = method[:-len("Stream")] or "__call__"
        return dep, method, stream

    def _call_unary(self, dep: str, method: str, request: "_GrpcRequest"):
        from ray_tpu.serve._private.router import get_router

        import ray_tpu

        router = get_router(self._proxy.controller_name, dep)
        ref = router.assign(method, (request,), {})
        return _encode(ray_tpu.get(ref, timeout=60))

    def _call_stream(self, dep: str, method: str, request: "_GrpcRequest"):
        from ray_tpu.serve._private.router import get_router

        import ray_tpu

        router = get_router(self._proxy.controller_name, dep)
        gen = router.assign(method, (request,), {}, streaming=True)
        for ref in gen:
            yield _encode(ray_tpu.get(ref, timeout=60))


class _Handler:
    """grpc.GenericRpcHandler serving every /ray_tpu.serve.* method."""

    def __init__(self, ingress: GrpcIngress):
        self._ingress = ingress
        import grpc

        self._grpc = grpc

    def service(self, handler_call_details):
        grpc = self._grpc
        routed = self._ingress._route(handler_call_details.method)
        if routed is None:
            return None
        dep, method, stream = routed
        if dep not in set(self._ingress._proxy.routes.values()):
            # Unknown deployment: answer UNIMPLEMENTED immediately from the
            # proxy's route table. Falling through to the router would
            # block the handler thread for the full replica wait AND cache
            # a Router (two live threads) per bogus name — a trivial
            # resource-exhaustion vector on a public port.
            return None
        md = dict(handler_call_details.invocation_metadata or ())

        ident = lambda b: b  # noqa: E731 — payloads are raw bytes

        if stream:
            def handle_stream(request_bytes, context):
                req = _GrpcRequest(handler_call_details.method,
                                   request_bytes, md)
                try:
                    yield from self._ingress._call_stream(dep, method, req)
                except Exception as e:
                    from ray_tpu.exceptions import BackPressureError

                    if isinstance(e, BackPressureError):
                        # Shed by admission control: RESOURCE_EXHAUSTED is
                        # the canonical gRPC back-pressure code (clients
                        # back off), not INTERNAL (clients report a bug).
                        context.abort(
                            grpc.StatusCode.RESOURCE_EXHAUSTED, str(e))
                    logger.error("grpc stream %s failed: %r",
                                 handler_call_details.method, e)
                    context.abort(grpc.StatusCode.INTERNAL, repr(e))

            return grpc.unary_stream_rpc_method_handler(
                handle_stream, request_deserializer=ident,
                response_serializer=ident)

        def handle_unary(request_bytes, context):
            req = _GrpcRequest(handler_call_details.method, request_bytes, md)
            try:
                return self._ingress._call_unary(dep, method, req)
            except Exception as e:
                from ray_tpu.exceptions import BackPressureError

                if isinstance(e, BackPressureError):
                    context.abort(
                        grpc.StatusCode.RESOURCE_EXHAUSTED, str(e))
                logger.error("grpc %s failed: %r",
                             handler_call_details.method, e)
                context.abort(grpc.StatusCode.INTERNAL, repr(e))

        return grpc.unary_unary_rpc_method_handler(
            handle_unary, request_deserializer=ident,
            response_serializer=ident)
