"""Serve controller: reconciles declared deployments into replica actors.

Parity target: reference python/ray/serve/_private/controller.py:86
(ServeController.run_control_loop) + deployment_state.py:1248,2343 (the
reconciler: scale up/down, rolling updates, health checks) +
long_poll.py (LongPollHost — version-gated config push to routers/proxies)
+ autoscaling_policy.py (ongoing-requests-based replica count).

One async actor; the reconcile loop runs as a background task on its event
loop. Routing state is versioned; get_routing()/route_table() long-poll
until the version advances (or time out), which is how routers and proxies
learn about replica membership changes without polling hot loops.
"""

from __future__ import annotations

import asyncio
import logging
import math
import time
import uuid
from typing import Any, Optional

import ray_tpu
from ray_tpu._private.events import emit_event

logger = logging.getLogger(__name__)

CONTROLLER_NAME = "_serve_controller"
PROXY_NAME = "_serve_proxy"
RECONCILE_INTERVAL_S = 0.2
AUTOSCALE_INTERVAL_S = 0.5
HEALTH_INTERVAL_S = 1.0
DOWNSCALE_PATIENCE = 4  # consecutive intervals below target before shrink


class _DeploymentState:
    def __init__(self, spec: dict):
        self.spec = spec
        self.replicas: dict[str, dict] = {}  # rid -> {handle, ready}
        self.stopping: list = []  # handles being drained
        self.low_ticks = 0  # autoscale downscale patience
        self.target = self._initial_target()

    def _initial_target(self) -> int:
        n = self.spec.get("num_replicas", 1)
        if self.spec.get("autoscaling_config"):
            return int(self.spec["autoscaling_config"].get("min_replicas", 1))
        return int(n)

    def ready_replicas(self) -> list[tuple[str, Any]]:
        return [(rid, r["handle"]) for rid, r in self.replicas.items()
                if r["ready"]]


class ServeController:
    def __init__(self):
        self.deployments: dict[str, _DeploymentState] = {}
        self.routes: dict[str, str] = {}  # route_prefix -> deployment name
        self.version = 0
        self._version_event: Optional[asyncio.Event] = None
        self._loop_task = None
        self._shutdown = False
        # rolling updates: deployment -> old-generation replicas still
        # serving until the new generation is ready
        self._retire_after_ready: dict[str, dict] = {}
        self._health_inflight: set[str] = set()
        # HTTP proxy fleet registry (README "Cross-host streaming &
        # multi-proxy"): proxy_id -> {host, port, pid}. Proxies register
        # on ready() — including after a restart, which is how a SIGKILLed
        # proxy rejoins the fleet — and serve.proxy_ports() reads it.
        self._proxies: dict[str, dict] = {}

    # ------------------------------------------------------------ plumbing
    def _ensure_loop(self):
        if self._version_event is None:
            self._version_event = asyncio.Event()
        if self._loop_task is None:
            self._loop_task = asyncio.ensure_future(self._control_loop())

    def _bump(self):
        self.version += 1
        if self._version_event is not None:
            self._version_event.set()
            self._version_event = asyncio.Event()

    async def _wait_version(self, known: int, timeout: float):
        deadline = time.monotonic() + timeout
        while self.version == known and not self._shutdown:
            left = deadline - time.monotonic()
            if left <= 0:
                return
            self._ensure_loop()
            try:
                await asyncio.wait_for(asyncio.shield(self._version_event.wait()),
                                       timeout=min(left, 1.0))
            except asyncio.TimeoutError:
                pass

    # ------------------------------------------------------------- public
    async def deploy(self, spec: dict) -> None:
        """Register (or update) a deployment; reconciliation is async —
        poll status() for readiness (reference deploy path: client.deploy ->
        wait_for_deployment_healthy)."""
        self._ensure_loop()
        name = spec["name"]
        cur = self.deployments.get(name)
        if cur is not None and cur.spec.get("version") == spec.get("version"):
            # config-only update (e.g. num_replicas): keep replicas
            cur.spec = spec
            if not spec.get("autoscaling_config"):
                cur.target = int(spec.get("num_replicas", 1))
        else:
            st = _DeploymentState(spec)
            if cur is not None:
                # rolling update: keep old replicas serving; they are
                # retired once the new generation is ready. If an even
                # older generation is still parked here (two rapid
                # deploys), stop it now — nothing routes to it anymore.
                stale = self._retire_after_ready.pop(name, None)
                if stale:
                    for r in stale.values():
                        asyncio.ensure_future(self._stop_replica(r["handle"]))
                self._retire_after_ready[name] = cur.replicas
            self.deployments[name] = st
        prefix = spec.get("route_prefix")
        if prefix:
            self.routes = {p: d for p, d in self.routes.items() if d != name}
            self.routes[prefix] = name
        emit_event("serve_deploy",
                   f"deployment {name!r} "
                   f"{'updated' if cur is not None else 'created'} "
                   f"(target {self.deployments[name].target})",
                   entity=(name,),
                   attrs={"target": self.deployments[name].target,
                          "update": cur is not None})
        self._bump()

    async def get_routing(self, deployment: str, known_version: int = -1,
                          timeout: float = 10.0) -> dict:
        if known_version == self.version:
            await self._wait_version(known_version, timeout)
        st = self.deployments.get(deployment)
        reps = st.ready_replicas() if st else []
        # During a rolling update the outgoing generation keeps serving
        # until the new one is ready (no dropped requests).
        retire = self._retire_after_ready.get(deployment)
        if retire and not reps:
            reps = [(rid, r["handle"]) for rid, r in retire.items() if r["ready"]]
        out = {"version": self.version, "replicas": reps}
        from ray_tpu._private.rtconfig import CONFIG

        if CONFIG.serve_admission and st is not None:
            # Admission budgets ride the same long-poll frame as
            # membership, so routers learn cap changes exactly when they
            # learn replica changes. Absent entirely with the plane off —
            # the frame stays byte-identical to the pre-admission shape.
            out["budgets"] = {
                "max_ongoing": int(st.spec.get("max_ongoing_requests", 16)),
                "max_queued": int(st.spec.get("max_queued_requests", -1)),
                "queue_deadline_s": st.spec.get("queue_deadline_s"),
            }
        return out

    async def route_table(self, known_version: int = -1,
                          timeout: float = 10.0) -> dict:
        if known_version == self.version:
            await self._wait_version(known_version, timeout)
        return {"version": self.version, "routes": dict(self.routes)}

    async def status(self) -> dict:
        out = {}
        for name, st in self.deployments.items():
            ready = len(st.ready_replicas())
            out[name] = {
                "target": st.target,
                "ready": ready,
                # target==0 is a VALID steady state for scaled-to-zero
                # deployments (min_replicas=0), not an in-progress update.
                "status": ("RUNNING" if ready >= st.target
                           and (st.target > 0 or self._scale_to_zero_ok(st))
                           else "UPDATING"),
            }
        return out

    async def register_proxy(self, proxy_id: str, host: str, port: int,
                             pid: int) -> None:
        """Called by each HTTP proxy from ready(). Re-registration under
        the same proxy_id (a restarted proxy, whose port/pid changed) is
        an update, not an error — that IS the rejoin contract."""
        self._proxies[proxy_id] = {
            "host": host, "port": int(port), "pid": int(pid)}

    async def list_proxies(self) -> dict:
        """proxy_id -> {host, port, pid} for every proxy that has come up.
        Backs serve.proxy_ports() and the /v1/stats fleet aggregation."""
        return {k: dict(v) for k, v in self._proxies.items()}

    async def delete(self, name: str):
        st = self.deployments.pop(name, None)
        self.routes = {p: d for p, d in self.routes.items() if d != name}
        if st is not None:
            for rid, r in st.replicas.items():
                asyncio.ensure_future(self._stop_replica(r["handle"]))
        retired = self._retire_after_ready.pop(name, None)
        if retired:
            for r in retired.values():
                asyncio.ensure_future(self._stop_replica(r["handle"]))
        self._bump()

    async def shutdown_all(self):
        self._shutdown = True
        for name in list(self.deployments):
            await self.delete(name)
        return True

    # ----------------------------------------------------------- reconcile
    async def _control_loop(self):
        last_autoscale = 0.0
        last_health = 0.0
        while not self._shutdown:
            try:
                now = time.monotonic()
                for name, st in list(self.deployments.items()):
                    await self._reconcile(name, st)
                if now - last_autoscale >= AUTOSCALE_INTERVAL_S:
                    last_autoscale = now
                    for name, st in list(self.deployments.items()):
                        if st.spec.get("autoscaling_config"):
                            await self._autoscale(name, st)
                if now - last_health >= HEALTH_INTERVAL_S:
                    last_health = now
                    for name, st in list(self.deployments.items()):
                        for rid, r in list(st.replicas.items()):
                            if r["ready"] and rid not in self._health_inflight:
                                self._health_inflight.add(rid)
                                asyncio.ensure_future(
                                    self._check_replica(name, st, rid, r["handle"]))
            except Exception:
                logger.exception("serve controller reconcile error")
            await asyncio.sleep(RECONCILE_INTERVAL_S)

    async def _check_replica(self, name: str, st: _DeploymentState,
                             rid: str, handle):
        """Dead-replica detection (reference deployment_state health checks):
        an unhealthy replica leaves the routing table immediately; the
        reconciler replaces it on the next tick."""
        try:
            await self._async_get(handle.health_check.remote(), timeout=5)
        except Exception as e:
            if (name in self.deployments and self.deployments[name] is st
                    and st.replicas.pop(rid, None) is not None):
                logger.warning("serve: replica %s failed health check (%r); "
                               "replacing", rid, e)
                emit_event("serve_replica_death",
                           f"replica {rid} failed its health check ({e!r}); "
                           f"replacing", entity=(name, rid))
                self._bump()
                # Actually stop it: a live-but-stuck replica would otherwise
                # keep its actor + resource reservation forever, starving
                # the replacement.
                asyncio.ensure_future(self._stop_replica(handle))
        finally:
            self._health_inflight.discard(rid)

    async def _reconcile(self, name: str, st: _DeploymentState):
        # Scale up.
        while len(st.replicas) < st.target:
            self._start_replica(name, st)
        # Promote replicas whose ready() resolved. wait/get are synchronous
        # cluster RPCs; even a timeout=0 poll pays a controller round trip,
        # so both hop through the executor — this loop shares the actor's
        # event loop with the long-poll handlers and health replies.
        for rid, r in list(st.replicas.items()):
            if not r["ready"] and r["ready_ref"] is not None:
                done, _ = await self._async_wait([r["ready_ref"]])
                if not done:
                    continue
                err = None
                try:
                    await self._async_get(done[0], timeout=1)
                except Exception as e:
                    err = e
                if self.deployments.get(name) is not st:
                    # Superseded mid-await: st.replicas may now BE the
                    # retire set deploy() parked in _retire_after_ready —
                    # popping a failed replica from it here would exempt
                    # that actor from the retire sweep and leak it.
                    return
                if err is None:
                    r["ready"] = True
                    r["ready_ref"] = None
                    self._bump()
                else:
                    logger.warning("serve: replica %s failed to start: %r",
                                   rid, err)
                    emit_event("serve_replica_death",
                               f"replica {rid} failed to start: {err!r}",
                               entity=(name, rid), attrs={"start": True})
                    st.replicas.pop(rid, None)
        # The executor hops above are suspension points the old sync
        # wait/get never had: a deploy() landing mid-await swaps
        # self.deployments[name] to a NEW generation's state and points
        # _retire_after_ready at the generation WE hold. Running the
        # retire/scale-down logic against the stale st would count the old
        # generation's own replicas as "the new one is ready" and stop it
        # before its successor serves — bail out and let the next tick
        # reconcile the live state.
        if self.deployments.get(name) is not st:
            return
        # Finish a rolling update: retire the old generation once the new
        # one is fully ready.
        old = self._retire_after_ready.get(name)
        if old and len(st.ready_replicas()) >= max(1, st.target):
            self._retire_after_ready.pop(name, None)
            self._bump()  # routers switch to the new generation NOW
            for rid, r in old.items():
                asyncio.ensure_future(self._stop_replica(r["handle"]))
        # Scale down (newest first, like the reference's replica selection).
        while len(st.replicas) > st.target:
            rid = next(reversed(st.replicas))
            r = st.replicas.pop(rid)
            self._bump()
            asyncio.ensure_future(self._stop_replica(r["handle"]))

    def _start_replica(self, name: str, st: _DeploymentState):
        spec = st.spec
        rid = f"{name}#{uuid.uuid4().hex[:6]}"
        opts = dict(spec.get("ray_actor_options") or {})
        opts.setdefault("num_cpus", 1)
        cap = int(spec.get("max_ongoing_requests", 16))
        opts["max_concurrency"] = cap
        from ray_tpu._private.rtconfig import CONFIG
        from ray_tpu.serve._private.replica import Replica

        extra: dict = {}
        if CONFIG.serve_admission:
            # With admission on, the replica enforces the cap itself
            # (typed replica_busy rejection the routers retry elsewhere).
            # The actor concurrency limit gets headroom above the cap so
            # control calls — stats, drain, the rejection itself — still
            # run while every request slot is occupied; without it a
            # saturated replica is also unobservable.
            opts["max_concurrency"] = cap + 8
            extra["max_ongoing"] = cap
        actor_cls = ray_tpu.remote(**opts)(Replica)
        handle = actor_cls.remote(name, rid, spec["callable"],
                                  tuple(spec.get("init_args") or ()),
                                  dict(spec.get("init_kwargs") or {}),
                                  **extra)
        st.replicas[rid] = {"handle": handle, "ready": False,
                            "ready_ref": handle.ready.remote()}

    async def _stop_replica(self, handle):
        try:
            ref = handle.drain.remote(5.0)
            await self._async_get(ref, timeout=8)
        except Exception:
            pass
        try:
            ray_tpu.kill(handle)
        except Exception:
            pass

    @staticmethod
    def _scale_to_zero_ok(st: "_DeploymentState") -> bool:
        cfg = st.spec.get("autoscaling_config") or {}
        return int(cfg.get("min_replicas", 1)) == 0

    async def notify_demand(self, name: str):
        """A router has requests waiting with ZERO replicas up: scale from
        zero immediately (reference: handle/router demand metrics feeding
        autoscaling so min_replicas=0 deployments wake on traffic)."""
        st = self.deployments.get(name)
        if st is None:
            return False
        # Only autoscaled scale-to-zero deployments wake on demand: an
        # operator who explicitly set num_replicas=0 paused the deployment
        # and a waiting client must not override that.
        if st.target < 1 and self._scale_to_zero_ok(st):
            logger.info("serve: scale-from-zero %s (router demand)", name)
            emit_event("serve_scale",
                       f"deployment {name!r} scale-from-zero 0 -> 1 "
                       f"(router demand)", entity=(name,),
                       attrs={"from": 0, "to": 1, "why": "demand"})
            st.target = 1
            st.low_ticks = 0
        return True

    async def _autoscale(self, name: str, st: _DeploymentState):
        cfg = st.spec["autoscaling_config"]
        lo = int(cfg.get("min_replicas", 1))
        hi = int(cfg.get("max_replicas", max(lo, 1)))
        target_ongoing = float(cfg.get("target_ongoing_requests", 2))
        target_latency = cfg.get("target_latency_ms")  # None = off
        reps = st.ready_replicas()
        if not reps:
            return
        total = 0
        lat_sum, lat_n = 0.0, 0
        for _rid, h in reps:
            try:
                s = await self._async_get(h.stats.remote(), timeout=2)
                total += s["ongoing"]
                if s.get("total"):
                    lat_sum += s.get("ema_latency_ms", 0.0)
                    lat_n += 1
            except Exception:
                pass
        desired = max(lo, min(hi, math.ceil(total / target_ongoing) or lo))
        if target_latency and lat_n:
            # Target-latency policy (reference autoscaling_policy's
            # latency-target variant): replicas scale with observed mean
            # latency over the target; combined with the ongoing-requests
            # policy by taking the tighter (larger) answer.
            mean_lat = lat_sum / lat_n
            by_latency = math.ceil(
                len(reps) * mean_lat / float(target_latency))
            desired = max(desired, min(hi, max(lo, by_latency)))
        if desired > st.target:
            logger.info("serve: autoscale %s %d -> %d (ongoing=%d)",
                        name, st.target, desired, total)
            emit_event("serve_scale",
                       f"deployment {name!r} autoscale {st.target} -> "
                       f"{desired} (ongoing={total})", entity=(name,),
                       attrs={"from": st.target, "to": desired,
                              "ongoing": total})
            st.target = desired
            st.low_ticks = 0
        elif desired < st.target:
            st.low_ticks += 1
            if st.low_ticks >= DOWNSCALE_PATIENCE:
                logger.info("serve: autoscale %s %d -> %d (ongoing=%d)",
                            name, st.target, desired, total)
                emit_event("serve_scale",
                           f"deployment {name!r} autoscale {st.target} -> "
                           f"{desired} (ongoing={total})", entity=(name,),
                           attrs={"from": st.target, "to": desired,
                                  "ongoing": total})
                st.target = desired
                st.low_ticks = 0
        else:
            st.low_ticks = 0

    @staticmethod
    async def _async_get(ref, timeout: float):
        """Await an ObjectRef without blocking the actor event loop."""
        loop = asyncio.get_event_loop()
        return await loop.run_in_executor(None, lambda: ray_tpu.get(ref, timeout=timeout))

    @staticmethod
    async def _async_wait(refs, num_returns: int = 1, timeout: float = 0):
        """Poll ObjectRef readiness without blocking the actor event loop."""
        loop = asyncio.get_event_loop()
        return await loop.run_in_executor(
            None, lambda: ray_tpu.wait(refs, num_returns=num_returns,
                                       timeout=timeout))
