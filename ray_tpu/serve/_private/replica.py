"""Serve replica: the actor that hosts one copy of a deployment.

Parity target: reference python/ray/serve/_private/replica.py
(UserCallableWrapper + Replica — construct the user callable once, execute
requests with an ongoing-count the router/autoscaler read, drain before
shutdown). Replicas are async actors: concurrent requests interleave on the
actor's event loop up to max_ongoing_requests (reference replica
max_concurrent_queries).
"""

from __future__ import annotations

import asyncio
import contextvars
import inspect
import json as _json
import threading
import time
from typing import Any, Optional

#: Model id of the request currently being handled (reference
#: serve.get_multiplexed_model_id / _serve_request_context).
_multiplexed_model_id: contextvars.ContextVar[str] = contextvars.ContextVar(
    "rt_serve_multiplexed_model_id", default="")


class Request:
    """Minimal HTTP request view handed to deployments (the role of the
    reference's starlette.Request, proxy.py -> ASGI scope)."""

    def __init__(self, method: str = "GET", path: str = "/", query: dict | None = None,
                 headers: dict | None = None, body: bytes = b""):
        self.method = method
        self.path = path
        self.query = dict(query or {})
        self.headers = dict(headers or {})
        self.body = body

    def json(self):
        return _json.loads(self.body or b"null")

    @property
    def query_params(self) -> dict:
        return self.query

    def __repr__(self):
        return f"Request({self.method} {self.path})"


class Replica:
    """Wrapped by ray_tpu.remote at deploy time (controller attaches the
    deployment's resource options)."""

    def __init__(self, deployment: str, replica_id: str, callable_or_class,
                 init_args: tuple, init_kwargs: dict, max_ongoing: int = 0):
        self.deployment = deployment
        self.replica_id = replica_id
        if isinstance(callable_or_class, type):
            self.callable = callable_or_class(*init_args, **(init_kwargs or {}))
        else:
            self.callable = callable_or_class
        self.ongoing = 0
        self.total = 0
        # Hard cap on concurrently executing requests (0 = uncapped, the
        # pre-admission behavior). Routers reserve slots before they
        # dispatch, so rejections here only fire on cross-router races —
        # several routers each under their own count can still overshoot
        # the replica. The typed replica_busy rejection sends the request
        # back to the router's retry path instead of silently queueing it
        # on a saturated event loop.
        self.max_ongoing = int(max_ongoing)
        self._stream_pool = None  # lazy; see handle_request_streaming
        # EMA of request latency (ms): the target-latency autoscaling
        # signal (reference autoscaling_policy latency-based variants).
        self.ema_latency_ms = 0.0

    async def ready(self) -> str:
        """Constructor finished (actor creation ran __init__); used as the
        readiness barrier before a replica enters the routing table."""
        return self.replica_id

    def _admit_or_raise(self):
        if self.max_ongoing > 0 and self.ongoing >= self.max_ongoing:
            from ray_tpu.exceptions import BackPressureError

            raise BackPressureError(
                f"replica {self.replica_id} is at its concurrency cap "
                f"({self.ongoing}/{self.max_ongoing} ongoing)",
                deployment=self.deployment, reason="replica_busy",
                queued=0, retry_after_s=0.1)

    async def handle_request(self, method_name: str, args: tuple, kwargs: dict,
                             multiplexed_model_id: str = "",
                             bypass_cap: bool = False):
        # bypass_cap: operator introspection (stats probes) must succeed
        # exactly when the replica is saturated — the actor's concurrency
        # headroom (controller: cap + 8) keeps a lane open for them.
        if not bypass_cap:
            self._admit_or_raise()
        self.ongoing += 1
        self.total += 1
        _t0 = asyncio.get_event_loop().time()
        token = _multiplexed_model_id.set(multiplexed_model_id)
        try:
            # Calling the instance itself covers both function deployments
            # and class deployments' __call__.
            target = (self.callable if method_name == "__call__"
                      else getattr(self.callable, method_name))
            if inspect.iscoroutinefunction(target) or (
                    method_name == "__call__"
                    and inspect.iscoroutinefunction(
                        getattr(type(self.callable), "__call__", None))):
                out = target(*args, **(kwargs or {}))
            else:
                # SYNC user code must not block the replica's event loop —
                # it would serialize all in-flight requests and hide the
                # real ongoing count from the autoscaler/router. Context is
                # copied explicitly: run_in_executor does not propagate
                # contextvars (the multiplexed model id) on its own.
                loop = asyncio.get_event_loop()
                ctx = contextvars.copy_context()
                out = await loop.run_in_executor(
                    None, lambda: ctx.run(
                        lambda: target(*args, **(kwargs or {}))))
            if inspect.isawaitable(out):
                out = await out
            return out
        finally:
            _multiplexed_model_id.reset(token)
            self.ongoing -= 1
            dt_ms = (asyncio.get_event_loop().time() - _t0) * 1000.0
            self.ema_latency_ms = (0.8 * self.ema_latency_ms + 0.2 * dt_ms
                                   if self.total > 1 else dt_ms)

    def _pool(self):
        """Dedicated stream executor (NOT the default executor): long
        token streams park threads and must not starve handle_request's
        sync offloads."""
        if self._stream_pool is None:
            from concurrent.futures import ThreadPoolExecutor

            self._stream_pool = ThreadPoolExecutor(
                max_workers=64, thread_name_prefix="rt-repl-stream")
        return self._stream_pool

    # ------------------------------------------------- token-ring reply path
    @staticmethod
    def _ring_write(ring, rec, stop, park_s: float = 120.0) -> bool:
        """One record into the stream ring with bounded-park backpressure:
        a stalled/vanished consumer parks the producer (the ring is
        BOUNDED — nothing buffers unboundedly) until the stream is
        abandoned (stop) or the park cap trips. Returns False when the
        record could not be delivered (consumer gone)."""
        deadline = time.monotonic() + park_s
        while not stop.is_set() and time.monotonic() < deadline:
            try:
                ring.write(rec, timeout=0.2)
                return True
            except TimeoutError:
                continue  # ring full: consumer stalled; park bounded
            except Exception:
                return False  # ring closed/unlinked under us
        return False

    def _ring_pump(self, it, ring, stop) -> None:
        """Executor-side pump: drain a sync iterator into the stream ring
        (one record per item — items arrive pre-batched, e.g. one OpenAI
        chunk per decode chunk via GenStream.next_batch). Owns the
        iterator: on abandonment (stop) it closes it from THIS thread, so
        generator finalizers (engine slot release) always actually run —
        a cross-thread close() on an executing generator raises."""
        finished = False
        try:
            while not stop.is_set():
                try:
                    item = next(it)
                except StopIteration:
                    self._ring_write(ring, ("end", None), stop)
                    finished = True
                    return
                if not self._ring_write(ring, ("item", item), stop):
                    return
        except Exception as e:  # user iterator failure: attributed record
            self._ring_write(ring, ("err", repr(e)), stop)
            finished = True
        finally:
            if not finished:
                close = getattr(it, "close", None)
                if close is not None:
                    try:
                        close()
                    except Exception:
                        pass

    async def handle_request_streaming(self, method_name: str, args: tuple,
                                       kwargs: dict,
                                       multiplexed_model_id: str = "",
                                       stream_ring: Optional[dict] = None,
                                       bypass_cap: bool = False):
        """Streaming twin of handle_request: the user method returns an
        (async) generator/iterable whose items are yielded incrementally to
        the caller over the core streaming-generator transport (reference
        serve streaming responses / vLLM token streams). Called with
        num_returns='streaming' by the router/proxy.

        With `stream_ring` (README "Serving hot loop") the items ride a
        shm StreamRing straight to the proxy instead: ONE handshake item
        confirms attachment over the generator, then every item is a ring
        record — zero per-item ObjectRefs, per-item RPC, or per-item
        owner bookkeeping on the reply path. Without the kwarg this
        method is byte-identical to the classic path."""
        if not bypass_cap:
            self._admit_or_raise()
        self.ongoing += 1
        self.total += 1
        _t0 = asyncio.get_event_loop().time()
        token = _multiplexed_model_id.set(multiplexed_model_id)
        try:
            target = (self.callable if method_name == "__call__"
                      else getattr(self.callable, method_name))
            out = target(*args, **(kwargs or {}))
            if inspect.isawaitable(out):
                out = await out
            ring = None
            if stream_ring is not None and (
                    hasattr(out, "__anext__") or (
                        hasattr(out, "__iter__")
                        and not isinstance(out, (str, bytes, dict)))):
                from ray_tpu._private.rtconfig import CONFIG

                mode = "nak"
                if "name" in stream_ring and not CONFIG.stream_force_push:
                    try:
                        from ray_tpu.dag.stream import StreamRing

                        ring = StreamRing.attach(stream_ring)
                        mode = "ok"
                    except Exception:
                        ring = None  # cross-host / missing shm
                if (ring is None and stream_ring.get("push")
                        and CONFIG.stream_push):
                    # Same-host shm unavailable (remote replica): the
                    # push-stream carries the SAME record contract over
                    # rpc — write/close below are transport-agnostic.
                    # Connect setup blocks (socket + s_open round trip):
                    # keep it off the replica's event loop.
                    try:
                        from ray_tpu.dag.push_stream import PushStreamWriter

                        ring = await asyncio.get_event_loop(
                        ).run_in_executor(self._pool(), PushStreamWriter,
                                          stream_ring["push"])
                        mode = "push"
                    except Exception:
                        ring = None  # hub unreachable: classic path
                        mode = "nak"
                # The handshake is the ONLY generator item in ring/push
                # mode — the proxy reads it once, then drains the
                # transport.
                yield {"__rt_ring__": mode}
            if ring is not None:
                loop = asyncio.get_event_loop()
                stop = threading.Event()
                try:
                    if hasattr(out, "__anext__"):
                        # Async source: items produced on the loop, each
                        # ring write offloaded (it can park on
                        # backpressure — never block the replica loop).
                        try:
                            async for item in out:
                                ok = await loop.run_in_executor(
                                    self._pool(), self._ring_write,
                                    ring, ("item", item), stop)
                                if not ok:
                                    break
                            else:
                                await loop.run_in_executor(
                                    self._pool(), self._ring_write,
                                    ring, ("end", None), stop)
                        except Exception as e:
                            await loop.run_in_executor(
                                self._pool(), self._ring_write,
                                ring, ("err", repr(e)), stop)
                    else:
                        await loop.run_in_executor(
                            self._pool(), self._ring_pump,
                            iter(out), ring, stop)
                finally:
                    # Abandonment (gen_close -> aclose raises
                    # GeneratorExit at the await): stop tells the pump to
                    # exit and close its iterator from its own thread.
                    stop.set()
                    ring.close()
                return
            if hasattr(out, "__anext__"):
                async for item in out:
                    yield item
            elif hasattr(out, "__iter__") and not isinstance(
                    out, (str, bytes, dict)):
                # Sync iterables' next() may block on an engine stream:
                # use the dedicated pool (see _pool).
                pool = self._pool()
                loop = asyncio.get_event_loop()
                it = iter(out)
                sentinel = object()
                try:
                    while True:
                        item = await loop.run_in_executor(
                            pool, lambda: next(it, sentinel))
                        if item is sentinel:
                            break
                        yield item
                finally:
                    # Abandonment (gen_close -> aclose of this generator)
                    # must run the user iterator's finally blocks so
                    # engines can release per-request resources.
                    close = getattr(it, "close", None)
                    if close is not None:
                        try:
                            close()
                        except Exception:
                            pass
            else:
                yield out  # single-item "stream"
        finally:
            _multiplexed_model_id.reset(token)
            self.ongoing -= 1
            # Whole-stream duration: for autoscaling it reflects replica
            # occupancy, the quantity the latency target controls.
            dt_ms = (asyncio.get_event_loop().time() - _t0) * 1000.0
            self.ema_latency_ms = (0.8 * self.ema_latency_ms + 0.2 * dt_ms
                                   if self.total > 1 else dt_ms)

    def stats(self) -> dict:
        """SYNC deliberately: async methods queue behind the
        max_ongoing_requests semaphore, and the autoscaler must see the
        true ongoing count exactly when the replica is saturated (sync
        methods run on the exec thread / thread pool, not the loop)."""
        out = {"replica_id": self.replica_id, "ongoing": self.ongoing,
               "total": self.total, "ema_latency_ms": self.ema_latency_ms}
        if self.max_ongoing > 0:
            # Only with admission on (the controller passes the cap then):
            # the stats frame stays byte-identical with the plane off.
            out["max_ongoing"] = self.max_ongoing
        return out

    async def drain(self, timeout_s: float = 10.0) -> bool:
        """Wait for in-flight requests to finish (reference graceful
        shutdown, replica.py perform_graceful_shutdown)."""
        deadline = asyncio.get_event_loop().time() + timeout_s
        while self.ongoing > 0 and asyncio.get_event_loop().time() < deadline:
            await asyncio.sleep(0.02)
        return self.ongoing == 0

    def health_check(self) -> bool:
        """SYNC deliberately (see stats): a saturated-but-healthy replica
        must still answer within the controller's timeout, or it gets
        evicted exactly when it's doing its job. Process liveness is the
        primary signal (a dead actor fails the call itself). User
        check_health hooks run inline; awaitable results are driven on a
        private loop so an async probe still actually executes."""
        user_check = getattr(self.callable, "check_health", None)
        if user_check is None:
            return True
        out = user_check()
        if inspect.isawaitable(out):
            loop = asyncio.new_event_loop()
            try:
                loop.run_until_complete(out)
            except RuntimeError as e:
                msg = str(e).lower()
                # EXACT asyncio loop-affinity phrases only — a looser match
                # would misclassify user failures like "control loop
                # connection closed" as benign and skip eviction.
                affinity = ("bound to a different event loop",
                            "attached to a different loop",
                            "event loop is closed")
                if not any(p in msg for p in affinity):
                    raise  # a real user health failure must evict
                # Loop-affinity only (the hook touched serving-loop-bound
                # state): proves nothing about health — process liveness
                # already did the real check. Never evict over it.
                import logging

                logging.getLogger(__name__).warning(
                    "async check_health could not run on a private loop "
                    "(%r); treating as healthy", e)
            finally:
                loop.close()
        return True
