"""Client-side router: replica membership via long-poll, power-of-two
choices balancing, and DeploymentHandle.

Parity target: reference python/ray/serve/_private/router.py:321 (Router —
per-handle replica scheduling) + replica_scheduler/pow_2_scheduler.py:52
(sample two replicas, pick the lower outstanding count) + handle.py
(DeploymentHandle/DeploymentResponse).
"""

from __future__ import annotations

import logging
import random
import threading
import time
from typing import Any, Optional

import ray_tpu
from ray_tpu._private.rtconfig import CONFIG
from ray_tpu.exceptions import BackPressureError

logger = logging.getLogger(__name__)


class QueueCancelled(Exception):
    """The client abandoned a request while it was still QUEUED (never
    assigned): the proxy sets the request's cancel event on disconnect and
    the admission loop exits here — the queue slot frees immediately
    instead of riding out the deadline for nobody."""


def _retry_pause_s(attempt: int) -> float:
    """Jittered exponential backoff between replica-death re-assignments:
    full jitter (0.5x-1.5x) so a killed replica's whole backlog does not
    re-dispatch against the survivors in one synchronized wave."""
    base = max(0.001, float(CONFIG.serve_retry_base_s))
    return min(1.0, base * (2 ** attempt)) * (0.5 + random.random())


def _is_replica_busy(e: BaseException) -> bool:
    """A replica-side concurrency-cap rejection — raised in the replica so
    it crosses the wire wrapped in TaskError with the typed cause."""
    from ray_tpu.exceptions import TaskError

    if isinstance(e, BackPressureError):
        return e.reason == "replica_busy"
    return (isinstance(e, TaskError)
            and isinstance(getattr(e, "cause", None), BackPressureError)
            and e.cause.reason == "replica_busy")

_routers: dict[str, "Router"] = {}
_routers_lock = threading.Lock()


class AsyncResolver:
    """Bridges ObjectRef completion to asyncio futures with ONE background
    thread per event loop, so awaiting a response never parks a thread for
    the request duration (used by the HTTP proxy and by awaited
    DeploymentResponses inside async deployments)."""

    def __init__(self, loop):
        import asyncio  # noqa: F401 (loop comes from the caller)

        self._loop = loop
        self._pending: dict = {}  # ref -> asyncio future
        self._lock = threading.Lock()
        self._wake = threading.Event()
        threading.Thread(target=self._run, daemon=True,
                         name="serve-resolver").start()

    def submit(self, ref):
        fut = self._loop.create_future()
        with self._lock:
            self._pending[ref] = fut
        self._wake.set()
        return fut

    def _run(self):
        while True:
            if self._loop.is_closed():
                # Loop gone (serve torn down in this process): stop polling
                # and drop the registry entry so loop + thread can be GC'd.
                with _resolvers_lock:
                    if _loop_resolvers.get(id(self._loop)) is self:
                        _loop_resolvers.pop(id(self._loop), None)
                return
            with self._lock:
                refs = list(self._pending)
            if not refs:
                self._wake.wait(timeout=0.5)
                self._wake.clear()
                continue
            try:
                done, _ = ray_tpu.wait(refs, num_returns=1, timeout=0.1)
            except Exception:
                time.sleep(0.05)
                continue
            for ref in done:
                with self._lock:
                    fut = self._pending.pop(ref, None)
                if fut is None:
                    continue
                try:
                    val = ray_tpu.get(ref, timeout=10)
                    err = None
                except Exception as e:  # noqa: BLE001
                    val, err = None, e
                try:
                    self._loop.call_soon_threadsafe(_resolve_fut, fut, val, err)
                except RuntimeError:
                    pass  # loop closed under us


def _resolve_fut(fut, val, err):
    if fut.done():
        return
    if err is not None:
        fut.set_exception(err)
    else:
        fut.set_result(val)


_loop_resolvers: dict = {}
_resolvers_lock = threading.Lock()


def resolver_for(loop) -> AsyncResolver:
    with _resolvers_lock:
        r = _loop_resolvers.get(id(loop))
        if r is None:
            r = _loop_resolvers[id(loop)] = AsyncResolver(loop)
        return r


def get_router(controller_name: str, deployment: str) -> "Router":
    key = f"{controller_name}/{deployment}"
    with _routers_lock:
        r = _routers.get(key)
        if r is None or r.dead:
            r = _routers[key] = Router(controller_name, deployment)
        return r


def reset_routers():
    with _routers_lock:
        for r in _routers.values():
            r.close()
        _routers.clear()


class Router:
    def __init__(self, controller_name: str, deployment: str):
        self.controller_name = controller_name
        self.deployment = deployment
        self.dead = False
        self._replicas: list[tuple[str, Any]] = []
        self._version = -1
        self._have_replicas = threading.Event()
        self._outstanding: dict[str, int] = {}
        self._tracked: dict = {}  # result ref -> replica id
        # model id -> replica ids this router sent that model to (cache
        # locality for multiplexed deployments; router-local knowledge —
        # a wrong guess only costs the replica a model reload).
        self._model_replicas: dict[str, list] = {}
        # Reentrant: shed accounting (record_shed) runs under the queue
        # condition, which shares this lock.
        self._lock = threading.RLock()
        # Admission plane (README "Overload & admission control"): budgets
        # arrive on the routing long-poll frame when RT_SERVE_ADMISSION is
        # on (None keeps the legacy uncapped path). The condition shares
        # the router lock; the drain loop notifies as slots free and the
        # long-poll notifies on membership changes, so queued requests
        # wake exactly when assignment might newly succeed.
        self._budgets: Optional[dict] = None
        self._slots = threading.Condition(self._lock)
        self._queued = 0
        self._shed_total = 0
        self._shed_counts: dict[str, int] = {}
        self._last_shed_t = 0.0  # last shed (overload-transition detector)
        self._last_shed_event_t = 0.0  # last serve_shed event (throttle)
        self._closed = threading.Event()
        threading.Thread(target=self._longpoll_loop, daemon=True,
                         name=f"serve-router-{deployment}").start()
        threading.Thread(target=self._drain_loop, daemon=True,
                         name=f"serve-drain-{deployment}").start()

    # ------------------------------------------------------------ membership
    def _longpoll_loop(self):
        while not self._closed.is_set():
            try:
                controller = ray_tpu.get_actor(self.controller_name)
                rep = ray_tpu.get(
                    controller.get_routing.remote(
                        self.deployment, self._version, 10.0), timeout=15)
                with self._lock:
                    self._version = rep["version"]
                    self._replicas = list(rep["replicas"])
                    self._budgets = rep.get("budgets")
                    live = {rid for rid, _h in self._replicas}
                    self._outstanding = {
                        rid: n for rid, n in self._outstanding.items()
                        if rid in live}
                    self._model_replicas = {
                        m: [r for r in rids if r in live]
                        for m, rids in self._model_replicas.items()}
                    # Fresh replicas may have free slots for queued work.
                    self._slots.notify_all()
                if self._replicas:
                    self._have_replicas.set()
                else:
                    self._have_replicas.clear()
            except Exception as e:
                if self._closed.is_set():
                    return
                logger.debug("serve router long-poll error: %r", e)
                time.sleep(0.2)

    def _drain_loop(self):
        """Decrement outstanding counts as responses resolve — the
        client-side queue-length signal pow-2 balancing reads (reference
        RouterMetricsManager.dec_num_running_requests_for_replica)."""
        while not self._closed.is_set():
            with self._lock:
                refs = list(self._tracked)
            if not refs:
                time.sleep(0.005)
                continue
            try:
                done, _ = ray_tpu.wait(refs, num_returns=1, timeout=0.2)
            except Exception:
                time.sleep(0.05)
                continue
            if done:
                with self._lock:
                    for ref in done:
                        rid = self._tracked.pop(ref, None)
                        if rid is not None and rid in self._outstanding:
                            self._outstanding[rid] = max(
                                0, self._outstanding[rid] - 1)
                    # A finished request is a freed slot: wake the queue.
                    self._slots.notify_all()

    # ----------------------------------------------------------- admission
    def record_shed(self, reason: str, n: int = 1):
        """Account one shed: stats counter, metrics, and a THROTTLED event
        (sheds arrive at offered-load rate under overload — one aggregate
        serve_shed event per window, plus a serve_overload marker on the
        transition into saturation after a quiet period)."""
        from ray_tpu._private.events import emit_event
        from ray_tpu.util import metrics

        now = time.monotonic()
        with self._lock:
            self._shed_total += n
            self._shed_counts[reason] = self._shed_counts.get(reason, 0) + n
            quiet = now - self._last_shed_t > 5.0
            self._last_shed_t = now
            flush = now - self._last_shed_event_t > 2.0
            counts = None
            if flush:
                self._last_shed_event_t = now
                counts, self._shed_counts = self._shed_counts, {}
        metrics.SERVE_SHED.inc(n, tags={"deployment": self.deployment,
                                        "reason": reason})
        if quiet:
            emit_event("serve_overload",
                       f"deployment {self.deployment!r} is shedding "
                       f"({reason})", entity=(self.deployment,),
                       attrs={"reason": reason})
        if counts:
            emit_event("serve_shed",
                       f"deployment {self.deployment!r} shed "
                       f"{sum(counts.values())} request(s)",
                       entity=(self.deployment,), attrs=counts)

    def _shed(self, reason: str, queued: int, retry_after_s: float,
              detail: str):
        self.record_shed(reason)
        raise BackPressureError(
            f"request to deployment {self.deployment!r} shed: {detail}",
            deployment=self.deployment, reason=reason, queued=queued,
            retry_after_s=retry_after_s)

    def admission_stats(self) -> Optional[dict]:
        """Queue/shed visibility for /v1/stats (None with the plane off)."""
        b = self._budgets
        if b is None or not CONFIG.serve_admission:
            return None
        qdl = b.get("queue_deadline_s")
        with self._lock:
            return {"queued": self._queued, "shed_total": self._shed_total,
                    "max_ongoing_requests": int(b.get("max_ongoing", 16)),
                    "max_queued_requests": int(b.get("max_queued", -1)),
                    "queue_deadline_s": (float(CONFIG.serve_queue_deadline_s)
                                         if qdl is None else float(qdl))}

    def _pick_free_locked(self, cap: int, multiplexed_model_id: str):
        """Pow-2 choices among replicas with a FREE slot (outstanding under
        the deployment's per-replica cap); None when every replica is at
        capacity. Lock held by the caller. Multiplexed requests keep the
        hot-replica preference, constrained to free replicas."""
        reps = self._replicas
        if multiplexed_model_id and reps:
            known = self._model_replicas.get(multiplexed_model_id, ())
            hot = [(r, h) for r, h in reps if r in known]
            if hot:
                floor = min(self._outstanding.get(r, 0) for r, _h in reps)
                hot_floor = min(self._outstanding.get(r, 0)
                                for r, _h in hot)
                if hot_floor - floor <= 2:
                    reps = hot
        free = [(r, h) for r, h in reps
                if self._outstanding.get(r, 0) < cap]
        if not free:
            return None
        if len(free) == 1:
            return free[0]
        (r1, h1), (r2, h2) = random.sample(free, 2)
        if self._outstanding.get(r1, 0) <= self._outstanding.get(r2, 0):
            return r1, h1
        return r2, h2

    def _demand_ping(self):
        try:
            ctrl = ray_tpu.get_actor(self.controller_name)
            ctrl.notify_demand.remote(self.deployment)
        except Exception:
            pass

    def _admit(self, budgets: dict, timeout: float,
               multiplexed_model_id: str,
               cancel: Optional[threading.Event]):
        """Bounded-queue admission (README "Overload & admission control"):
        reserve a replica slot under the deployment's concurrency cap, or
        wait in the bounded queue until one frees — shedding with a typed
        BackPressureError when the queue is full or the deadline passes,
        NEVER stalling past it. Returns (rid, handle) with the slot
        already reserved (outstanding incremented)."""
        from ray_tpu.util import metrics

        cap = max(1, int(budgets.get("max_ongoing", 16)))
        max_queued = int(budgets.get("max_queued", -1))
        qdl = budgets.get("queue_deadline_s")
        qdl = float(CONFIG.serve_queue_deadline_s) if qdl is None else float(qdl)
        deadline = time.monotonic() + max(0.0, min(timeout, qdl))
        retry_after = min(2.0, max(0.1, qdl / 4.0))
        last_demand_ping = 0.0
        tags = {"deployment": self.deployment}
        with self._slots:
            # Fast path first: a free slot now means no queue entry at all.
            picked = self._pick_free_locked(cap, multiplexed_model_id)
            if picked is None and 0 <= max_queued <= self._queued:
                self._shed("queue_full", self._queued, retry_after,
                           f"queue full ({self._queued}/{max_queued} "
                           f"queued, {cap} executing per replica)")
            enqueued = picked is None
            if enqueued:
                self._queued += 1
                metrics.SERVE_QUEUE_DEPTH.set(self._queued, tags=tags)
            try:
                while picked is None:
                    if cancel is not None and cancel.is_set():
                        raise QueueCancelled(self.deployment)
                    now = time.monotonic()
                    if not self._replicas and now - last_demand_ping >= 1.0:
                        # Scale-from-zero demand signal (see the legacy
                        # path); the RPC submit must not hold the lock.
                        last_demand_ping = now
                        self._slots.release()
                        try:
                            self._demand_ping()
                        finally:
                            self._slots.acquire()
                        continue  # membership may have changed meanwhile
                    left = deadline - now
                    if left <= 0:
                        self._shed("deadline", self._queued, retry_after,
                                   f"no replica slot within {qdl}s "
                                   f"(queue_deadline_s)")
                    # Bounded waits: the cancel event has no notifier, so
                    # poll it at 100ms granularity.
                    self._slots.wait(timeout=min(left, 0.1))
                    picked = self._pick_free_locked(cap, multiplexed_model_id)
            finally:
                if enqueued:
                    self._queued = max(0, self._queued - 1)
                    metrics.SERVE_QUEUE_DEPTH.set(self._queued, tags=tags)
            rid, handle = picked
            self._outstanding[rid] = self._outstanding.get(rid, 0) + 1
            return rid, handle

    # --------------------------------------------------------------- assign
    def assign(self, method_name: str, args: tuple, kwargs: dict,
               timeout: float = 30.0, multiplexed_model_id: str = "",
               streaming: bool = False, stream_ring: Optional[dict] = None,
               cancel: Optional[threading.Event] = None,
               meta: Optional[dict] = None, bypass_queue: bool = False):
        """Pick a replica and dispatch; returns the result ObjectRef — or,
        with streaming=True, an ObjectRefGenerator of incremental results
        (the replica method runs as a streaming generator; reference
        serve's streaming response path over RequestRouter).
        `stream_ring` (streaming only) asks the replica to deliver items
        over a shm StreamRing instead of per-item streamed ObjectRefs
        (README "Serving hot loop"); None keeps the classic reply path
        byte-identical. Multiplexed requests prefer replicas this router
        already routed the model to (reference multiplex cache locality),
        then fall back to pow-2-choices balancing.

        With admission on (RT_SERVE_ADMISSION + budgets on the routing
        frame) assignment goes through the bounded queue and may raise
        BackPressureError (see _admit); `cancel` aborts a QUEUED request
        on client disconnect, `meta` (a dict) receives the chosen
        replica_id for failure attribution, and `bypass_queue` exempts
        operator introspection (stats) so the queue stays observable
        exactly when it is full."""
        admitted = (CONFIG.serve_admission and self._budgets is not None
                    and not bypass_queue)
        if admitted:
            rid, handle = self._admit(self._budgets, timeout,
                                      multiplexed_model_id, cancel)
        else:
            rid, handle = self._pick_legacy(timeout, multiplexed_model_id)
            with self._lock:
                self._outstanding[rid] = self._outstanding.get(rid, 0) + 1
        if meta is not None:
            meta["replica_id"] = rid
        # Stats probes that bypassed the queue also bypass the replica's
        # hard cap — observability must work exactly when saturated.
        bypass_cap = bool(bypass_queue and CONFIG.serve_admission)
        return self._dispatch(rid, handle, method_name, args, kwargs,
                              multiplexed_model_id, streaming, stream_ring,
                              bypass_cap=bypass_cap)

    def _pick_legacy(self, timeout: float, multiplexed_model_id: str):
        """The pre-admission replica pick: spin against membership with a
        flat timeout, no caps, no queue bound (byte-identical legacy path,
        pinned by the RT_SERVE_ADMISSION=0 test)."""
        deadline = time.monotonic() + timeout
        last_demand_ping = 0.0
        while True:
            if not self._have_replicas.is_set():
                # Zero replicas with a request in hand: tell the controller
                # so a min_replicas=0 deployment scales FROM zero on
                # traffic (reference: router demand metrics feed
                # autoscaling). Once per second per waiting request.
                now = time.monotonic()
                if now - last_demand_ping >= 1.0:
                    last_demand_ping = now
                    try:
                        ctrl = ray_tpu.get_actor(self.controller_name)
                        ctrl.notify_demand.remote(self.deployment)
                    except Exception:
                        pass
            left = deadline - time.monotonic()
            # A set event returns from wait() immediately, so the 1s cap
            # only bounds the no-replica polls between demand pings.
            if left <= 0 or not self._have_replicas.wait(
                    timeout=min(left, 1.0)):
                if time.monotonic() >= deadline:
                    raise TimeoutError(
                        f"no ready replicas for deployment "
                        f"{self.deployment!r}")
                continue
            with self._lock:
                reps = self._replicas
                if multiplexed_model_id and reps:
                    known = self._model_replicas.get(multiplexed_model_id, ())
                    hot = [(r, h) for r, h in reps if r in known]
                    if hot:
                        # Spill to cold replicas when every hot one is
                        # clearly busier than the least-loaded replica —
                        # a popular model must not be capped at one
                        # replica's throughput.
                        floor = min(self._outstanding.get(r, 0)
                                    for r, _h in reps)
                        hot_floor = min(self._outstanding.get(r, 0)
                                        for r, _h in hot)
                        if hot_floor - floor <= 2:
                            reps = hot
                if not reps:
                    pass  # emptied between the event wait and the lock
                elif len(reps) == 1:
                    rid, handle = reps[0]
                    break
                else:
                    (r1, h1), (r2, h2) = random.sample(reps, 2)
                    if self._outstanding.get(r1, 0) <= self._outstanding.get(r2, 0):
                        rid, handle = r1, h1
                    else:
                        rid, handle = r2, h2
                    break
            time.sleep(0.02)  # rare: replica set emptied mid-assign
        return rid, handle

    def _dispatch(self, rid: str, handle, method_name: str, args: tuple,
                  kwargs: dict, multiplexed_model_id: str, streaming: bool,
                  stream_ring: Optional[dict], bypass_cap: bool = False):
        """Dispatch to the picked replica (slot already reserved) and track
        the result ref so the drain loop releases the slot on completion."""
        with self._lock:
            if multiplexed_model_id:
                lst = self._model_replicas.pop(multiplexed_model_id, [])
                if rid not in lst:
                    lst.append(rid)
                # Re-insert at the end so the bound below evicts the
                # least-recently-ROUTED id, not merely the oldest-inserted
                # (a still-hot model must survive one-off stale ids).
                self._model_replicas[multiplexed_model_id] = lst
                # Bound the map: ids are client-supplied (HTTP header) and
                # must not leak memory in a long-running proxy.
                while len(self._model_replicas) > 512:
                    self._model_replicas.pop(
                        next(iter(self._model_replicas)))
        if streaming:
            skw = {"multiplexed_model_id": multiplexed_model_id}
            if stream_ring is not None:
                skw["stream_ring"] = stream_ring
            if bypass_cap:
                skw["bypass_cap"] = True
            gen = handle.handle_request_streaming.options(
                num_returns="streaming").remote(
                    method_name, args, kwargs, **skw)
            with self._lock:
                # The completion sentinel resolves when the stream ends —
                # exactly when the request stops being "outstanding".
                self._tracked[gen.completed()] = rid
            return gen
        ukw = {"multiplexed_model_id": multiplexed_model_id}
        if bypass_cap:
            ukw["bypass_cap"] = True
        ref = handle.handle_request.remote(method_name, args, kwargs, **ukw)
        with self._lock:
            self._tracked[ref] = rid
        return ref

    def close(self):
        self.dead = True
        self._closed.set()


class DeploymentResponse:
    """reference serve/handle.py DeploymentResponse: a future for one
    request; .result() retries once on replica death (the router has
    already learned about the dead replica via long-poll by then)."""

    def __init__(self, router: Router, method_name: str, args, kwargs, ref,
                 multiplexed_model_id: str = ""):
        self._router = router
        self._method = method_name
        self._args, self._kwargs = args, kwargs
        self._ref = ref
        self._model_id = multiplexed_model_id

    def result(self, timeout_s: float = 60.0):
        from ray_tpu.exceptions import ActorDiedError, WorkerCrashedError

        if not CONFIG.serve_admission:
            try:
                return ray_tpu.get(self._ref, timeout=timeout_s)
            except (ActorDiedError, WorkerCrashedError):
                # replica died mid-request: route to a survivor once
                self._ref = self._router.assign(
                    self._method, self._args, self._kwargs,
                    multiplexed_model_id=self._model_id)
                return ray_tpu.get(self._ref, timeout=timeout_s)
        # Admission on: replica-death (and cross-router replica_busy)
        # failures re-assign against survivors under a per-request retry
        # budget with jittered backoff — a killed replica's backlog drains
        # through the survivors instead of failing at the first death.
        deadline = time.monotonic() + timeout_s
        retries = max(0, int(CONFIG.serve_retries))
        for attempt in range(retries + 1):
            try:
                return ray_tpu.get(
                    self._ref,
                    timeout=max(0.1, deadline - time.monotonic()))
            except (ActorDiedError, WorkerCrashedError) as e:
                if attempt >= retries:
                    raise
                logger.debug("serve response retry %d after %r",
                             attempt + 1, e)
            except Exception as e:
                if not _is_replica_busy(e) or attempt >= retries:
                    raise
            time.sleep(_retry_pause_s(attempt))
            self._ref = self._router.assign(
                self._method, self._args, self._kwargs,
                timeout=max(0.1, deadline - time.monotonic()),
                multiplexed_model_id=self._model_id)

    def __await__(self):
        """`await handle.method.remote(x)` inside async deployments —
        costs no thread while the downstream request runs (one shared
        resolver thread per loop; reference DeploymentResponse is
        awaitable the same way)."""
        return self._aresult().__await__()

    async def _aresult(self):
        import asyncio

        from ray_tpu.exceptions import ActorDiedError, WorkerCrashedError

        resolver = resolver_for(asyncio.get_event_loop())
        if not CONFIG.serve_admission:
            try:
                return await resolver.submit(self._ref)
            except (ActorDiedError, WorkerCrashedError):
                self._ref = self._router.assign(
                    self._method, self._args, self._kwargs,
                    multiplexed_model_id=self._model_id)
                return await resolver.submit(self._ref)
        retries = max(0, int(CONFIG.serve_retries))
        for attempt in range(retries + 1):
            try:
                return await resolver.submit(self._ref)
            except (ActorDiedError, WorkerCrashedError) as e:
                if attempt >= retries:
                    raise
                logger.debug("serve response retry %d after %r",
                             attempt + 1, e)
            except Exception as e:
                if not _is_replica_busy(e) or attempt >= retries:
                    raise
            await asyncio.sleep(_retry_pause_s(attempt))
            # assign can park in the admission queue: keep it off the loop.
            self._ref = await asyncio.get_event_loop().run_in_executor(
                None, lambda: self._router.assign(
                    self._method, self._args, self._kwargs,
                    multiplexed_model_id=self._model_id))

    def _to_object_ref(self):
        return self._ref


class DeploymentHandle:
    """Picklable handle (reference serve/handle.py:DeploymentHandle):
    carries (controller_name, deployment); the per-process router is
    reconstructed lazily after unpickle, so handles can be passed into
    other deployments for model composition."""

    def __init__(self, deployment: str,
                 controller_name: str = "_serve_controller",
                 method_name: str = "__call__",
                 multiplexed_model_id: str = "",
                 stream: bool = False):
        self.deployment = deployment
        self.controller_name = controller_name
        self.method_name = method_name
        self.multiplexed_model_id = multiplexed_model_id
        self.stream = stream

    @property
    def _router(self) -> Router:
        return get_router(self.controller_name, self.deployment)

    def options(self, method_name: Optional[str] = None,
                multiplexed_model_id: Optional[str] = None,
                stream: Optional[bool] = None) -> "DeploymentHandle":
        return DeploymentHandle(
            self.deployment, self.controller_name,
            method_name if method_name is not None else self.method_name,
            multiplexed_model_id if multiplexed_model_id is not None
            else self.multiplexed_model_id,
            stream if stream is not None else self.stream)

    def __getattr__(self, name: str) -> "DeploymentHandle":
        if name.startswith("_"):
            raise AttributeError(name)
        return DeploymentHandle(self.deployment, self.controller_name, name,
                                self.multiplexed_model_id, self.stream)

    def remote(self, *args, **kwargs):
        if self.stream:
            # ObjectRefGenerator of incremental results (reference
            # handle.options(stream=True) -> DeploymentResponseGenerator).
            return self._router.assign(
                self.method_name, args, kwargs,
                multiplexed_model_id=self.multiplexed_model_id,
                streaming=True)
        ref = self._router.assign(
            self.method_name, args, kwargs,
            multiplexed_model_id=self.multiplexed_model_id)
        return DeploymentResponse(self._router, self.method_name, args,
                                  kwargs, ref,
                                  multiplexed_model_id=self.multiplexed_model_id)

    def __reduce__(self):
        return (DeploymentHandle,
                (self.deployment, self.controller_name, self.method_name,
                 self.multiplexed_model_id, self.stream))

    def __repr__(self):
        return f"DeploymentHandle({self.deployment!r})"
