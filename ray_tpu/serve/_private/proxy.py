"""HTTP proxy: aiohttp server actor routing requests to deployments.

Parity target: reference python/ray/serve/_private/proxy.py:750 (ProxyActor
hosting an HTTP server per node; route table via long-poll; request ->
router -> replica; response assembly :1137). The server runs on the
replica actor's own asyncio loop (async actor), so request handling and
response awaits interleave without threads-per-request.
"""

from __future__ import annotations

import asyncio
import contextvars
import json
import logging
import math
import os
import threading
import time
from typing import Optional

import ray_tpu
from ray_tpu._private import tracing as _tracing
from ray_tpu._private.rtconfig import CONFIG
from ray_tpu.serve._private.replica import Request
from ray_tpu.serve._private.router import (
    QueueCancelled,
    _is_replica_busy,
    _retry_pause_s,
    get_router,
    resolver_for,
)

logger = logging.getLogger(__name__)


class _TokenBucket:
    """Burst-tolerant per-route rate limiter (RT_SERVE_RPS/RT_SERVE_BURST,
    README "Overload & admission control"): refills continuously at `rate`
    tokens/s up to `burst`, so short bursts pass at line rate and only
    sustained excess is shed — before it ever touches the router queue."""

    __slots__ = ("rate", "burst", "tokens", "stamp")

    def __init__(self, rate: float, burst: int, now: float):
        self.rate = rate
        self.burst = burst
        self.tokens = float(burst)
        self.stamp = now

    def take(self, now: float) -> float:
        """0.0 when a token was taken; else seconds until one refills."""
        self.tokens = min(float(self.burst),
                          self.tokens + (now - self.stamp) * self.rate)
        self.stamp = now
        if self.tokens >= 1.0:
            self.tokens -= 1.0
            return 0.0
        return (1.0 - self.tokens) / max(self.rate, 1e-9)


class Proxy:
    def __init__(self, controller_name: str, host: str = "127.0.0.1",
                 port: int = 8000, grpc_port: Optional[int] = None,
                 proxy_id: str = ""):
        self.controller_name = controller_name
        self.host, self.port = host, port
        self.grpc_port = grpc_port  # None = gRPC ingress off
        self._grpc_ingress = None
        # Identity in the controller's proxy registry / metric tags; the
        # default keeps single-proxy deployments stable across restarts.
        self.proxy_id = proxy_id or "_serve_proxy"
        self.routes: dict[str, str] = {}
        self._version = -1
        self._site = None
        self._started = False
        self._resolver = None
        self._stream_pool = None  # dedicated: SSE waits pin a thread each
        # route prefix -> token bucket (RT_SERVE_RPS); rebuilt when the
        # knobs change so tests can flip rates without a proxy restart.
        self._buckets: dict[str, _TokenBucket] = {}
        # deployment -> monotonic time of its last ring-handshake nak: a
        # peer that cannot attach (cross-host replica, no shared shm)
        # naks every request, so skip the 1MB ring setup/unlink for a
        # while instead of paying it per stream. Time-bounded (not
        # permanent) so a transient failure can't disable the ring path
        # for a deployment forever. With the push transport armed a
        # remote replica answers "push" instead of nakking, so this
        # backoff only fires when BOTH transports are out.
        self._ring_nak: dict[str, float] = {}
        # Push-stream hub (lazy; README "Cross-host streaming"): ONE rpc
        # server per proxy process accepting token-record frames from
        # replicas that cannot attach the shm ring.
        self._hub = None
        self._active_streams = 0
        # (monotonic, [proxy names]) — controller proxy-registry cache so
        # /v1/stats aggregation costs one controller round trip per ~2s,
        # not per request.
        self._proxy_registry_cache: tuple[float, list] = (-1e9, [])

    def _sweep_dead_rings(self) -> None:
        """Unlink /dev/shm stream-ring segments left by proxies that died
        without running their per-stream unlink (a SIGKILLed proxy leaks
        one ring segment per open stream). Ring names embed the creator
        pid, so a segment is debris exactly when that pid is gone — live
        proxies' rings are never touched."""
        import glob

        for path in glob.glob("/dev/shm/rtring_sse_*"):
            stem = os.path.basename(path)[len("rtring_sse_"):]
            try:
                pid = int(stem.split("_", 1)[0])
            except ValueError:
                continue  # foreign or pre-pid naming: leave it alone
            if pid == os.getpid():
                continue
            try:
                os.kill(pid, 0)
            except ProcessLookupError:
                try:
                    os.unlink(path)
                except OSError:
                    pass
            except PermissionError:
                pass  # alive under another uid

    async def ready(self) -> int:
        """Bind the HTTP server; returns the bound port."""
        if self._started:
            return self.port
        from aiohttp import web

        self._sweep_dead_rings()

        app = web.Application()
        app.router.add_route("*", "/{tail:.*}", self._handle)
        # handler_cancellation: aiohttp >= 3.9 no longer cancels handler
        # tasks when the client disconnects. The admission plane depends
        # on that cancellation to free QUEUED slots for abandoned
        # requests, so re-enable it — only with the plane on, keeping the
        # legacy path byte-identical.
        runner = web.AppRunner(app, access_log=None,
                               handler_cancellation=bool(
                                   CONFIG.serve_admission))
        await runner.setup()
        site = web.TCPSite(runner, self.host, self.port)
        await site.start()
        self._site = site
        self._started = True
        if self.port == 0:
            # Auto-bound (extra proxies of a multi-proxy fleet): report
            # the real port so serve.proxy_ports() can route clients.
            try:
                self.port = site._server.sockets[0].getsockname()[1]
            except Exception:
                pass
        self._resolver = resolver_for(asyncio.get_event_loop())
        # Populate the route table BEFORE declaring ready: serve.run
        # returns right after this, and the first request must not race
        # the initial long-poll to a 404.
        try:
            controller = ray_tpu.get_actor(self.controller_name)
            ref = controller.route_table.remote(-1, 0.0)
            rep = await asyncio.get_event_loop().run_in_executor(
                None, lambda r=ref: ray_tpu.get(r, timeout=10))
            self._version = rep["version"]
            self.routes = rep["routes"]
        except Exception as e:
            logger.warning("serve proxy initial route fetch failed: %r", e)
        # Join the controller's proxy registry: /v1/stats aggregation and
        # serve.shutdown() discover the fleet there, and a RESTARTED proxy
        # re-registers here — rejoining routing exactly like it joined.
        try:
            import os as _os

            controller = ray_tpu.get_actor(self.controller_name)
            ref = controller.register_proxy.remote(
                self.proxy_id, self.host, self.port, _os.getpid())
            await asyncio.get_event_loop().run_in_executor(
                None, lambda r=ref: ray_tpu.get(r, timeout=5))
            from ray_tpu._private.events import emit_event

            emit_event("serve_proxy_join",
                       f"proxy {self.proxy_id!r} serving "
                       f"{self.host}:{self.port}",
                       entity=(self.proxy_id,),
                       attrs={"port": self.port, "pid": _os.getpid()})
        except Exception as e:
            logger.debug("serve proxy registration skipped: %r", e)
        if self.grpc_port is not None and self._grpc_ingress is None:
            from ray_tpu.serve._private.grpc_proxy import GrpcIngress

            self._grpc_ingress = GrpcIngress(self, self.host, self.grpc_port)
            self.grpc_port = self._grpc_ingress.port
        asyncio.ensure_future(self._route_poll_loop())
        return self.port

    async def grpc_ready(self) -> Optional[int]:
        """Bound gRPC ingress port (None when disabled)."""
        return self.grpc_port

    async def ensure_grpc(self, grpc_port: Optional[int]) -> Optional[int]:
        """Start the gRPC ingress on an ALREADY-RUNNING proxy (serve.run
        reuses the detached proxy actor, so constructor args from the
        first run would otherwise silently win over a later grpc_port)."""
        if grpc_port is not None and self._grpc_ingress is None:
            from ray_tpu.serve._private.grpc_proxy import GrpcIngress

            self._grpc_ingress = GrpcIngress(self, self.host, grpc_port)
            self.grpc_port = self._grpc_ingress.port
        return self.grpc_port

    async def _route_poll_loop(self):
        while True:
            try:
                controller = ray_tpu.get_actor(self.controller_name)
                ref = controller.route_table.remote(self._version, 10.0)
                rep = await asyncio.get_event_loop().run_in_executor(
                    None, lambda r=ref: ray_tpu.get(r, timeout=15))
                self._version = rep["version"]
                self.routes = rep["routes"]
            except Exception as e:
                logger.debug("serve proxy route poll error: %r", e)
                await asyncio.sleep(0.2)

    def _match(self, path: str) -> Optional[tuple[str, str]]:
        best = None
        for prefix, dep in self.routes.items():
            norm = prefix.rstrip("/") or "/"
            if path == norm or path.startswith(norm + "/") or norm == "/":
                if best is None or len(norm) > len(best[0]):
                    best = (norm, dep)
        return best

    def _pool(self):
        if self._stream_pool is None:
            from concurrent.futures import ThreadPoolExecutor

            # NOT the default executor: each active stream parks a thread
            # in next() for its whole lifetime — and with admission on,
            # queued assigns park one up to the deadline — so exhausting
            # the shared pool would stall every other run_in_executor user
            # (route polls, legacy assigns) behind long waits.
            self._stream_pool = ThreadPoolExecutor(
                max_workers=256, thread_name_prefix="rt-sse")
        return self._stream_pool

    async def _ensure_hub(self):
        """Lazy per-process push-stream hub: nothing binds (or costs a
        frame) until the first streaming request with the push transport
        armed."""
        if self._hub is None:
            from ray_tpu.dag.push_stream import PushStreamHub

            hub = PushStreamHub()
            host = self.host if self.host not in ("0.0.0.0", "::") \
                else "127.0.0.1"
            await hub.start(host)
            self._hub = hub
        return self._hub

    async def admission_snapshot(self, deployment: str) -> dict:
        """This process's admission/stream counters — the unit /v1/stats
        aggregation sums across the proxy fleet."""
        import os as _os

        router = get_router(self.controller_name, deployment)
        snap = dict(router.admission_stats() or {})
        snap["pid"] = _os.getpid()
        snap["active_streams"] = self._active_streams
        return snap

    async def _peer_snapshots(self, dep: str) -> dict:
        """Admission snapshots of every OTHER registered proxy (empty for
        a single-proxy fleet — the common case costs one cached registry
        lookup and no peer calls). Dead/restarting peers are skipped; the
        reconciled registry catches up when they rejoin."""
        loop = asyncio.get_event_loop()
        now = loop.time()
        ts, names = self._proxy_registry_cache
        if now - ts > 2.0:
            try:
                controller = ray_tpu.get_actor(self.controller_name)
                ref = controller.list_proxies.remote()
                reg = await loop.run_in_executor(
                    None, lambda r=ref: ray_tpu.get(r, timeout=2))
                names = sorted(reg or {})
            except Exception:
                names = []
            self._proxy_registry_cache = (now, names)
        peers: dict = {}
        for name in names:
            if name == self.proxy_id:
                continue
            try:
                h = ray_tpu.get_actor(name)
                ref = h.admission_snapshot.remote(dep)
                snap = await loop.run_in_executor(
                    None, lambda r=ref: ray_tpu.get(r, timeout=2))
                if isinstance(snap, dict):
                    peers[name] = snap
            except Exception:
                continue
        return peers

    def _mint_request(self) -> None:
        try:
            from ray_tpu.util import metrics as _m

            _m.SERVE_PROXY_REQS.inc(1, tags={"proxy": self.proxy_id})
        except Exception:
            pass

    def _mint_stream(self, delta: int) -> None:
        self._active_streams = max(0, self._active_streams + delta)
        try:
            from ray_tpu.util import metrics as _m

            if delta > 0:
                _m.SERVE_PROXY_STREAMS.inc(1, tags={"proxy": self.proxy_id})
            _m.SERVE_PROXY_ACTIVE.set(float(self._active_streams),
                                      tags={"proxy": self.proxy_id})
        except Exception:
            pass

    def _bucket_shed(self, prefix: str, dep: str):
        """Front-door rate limit: returns a 429 response when the route's
        token bucket is dry, None to admit. Off unless RT_SERVE_RPS > 0."""
        rate = float(CONFIG.serve_rps)
        if rate <= 0:
            return None
        burst = max(1, int(CONFIG.serve_burst))
        now = time.monotonic()
        b = self._buckets.get(prefix)
        if b is None or b.rate != rate or b.burst != burst:
            b = self._buckets[prefix] = _TokenBucket(rate, burst, now)
        wait = b.take(now)
        if wait <= 0.0:
            return None
        try:
            # Rides the router's shed accounting so /v1/stats shed_total
            # and the rt_serve_shed metric cover front-door rejections too.
            get_router(self.controller_name, dep).record_shed("rate_limit")
        except Exception:
            pass
        from ray_tpu.exceptions import BackPressureError

        return self._shed_response(BackPressureError(
            f"route {prefix!r} over its rate limit "
            f"({rate:g} req/s, burst {burst})",
            deployment=dep, reason="rate_limit", retry_after_s=wait))

    @staticmethod
    def _shed_response(e):
        """Map a BackPressureError to HTTP: 429 for loads the client can
        back off from (rate limit, full queue, busy replicas), 503 for a
        request that already burned its queue deadline. Both carry
        Retry-After so well-behaved clients pace themselves."""
        from aiohttp import web

        status = 503 if e.reason == "deadline" else 429
        retry_after = max(1, math.ceil(float(e.retry_after_s or 1.0)))
        return web.json_response(
            {"error": {"type": "BackPressureError", "reason": e.reason,
                       "deployment": e.deployment, "queued": e.queued,
                       "retry_after_s": e.retry_after_s,
                       "message": str(e)}},
            status=status, headers={"Retry-After": str(retry_after)})

    @staticmethod
    def _death_response(dep: str, replica_id, e):
        """Replica died mid-request and the retry budget is spent: 503
        (retriable — the controller is already restarting it), naming the
        replica and where its fate is recorded. Distinct from the shed
        429s: THIS request was admitted and lost, not rejected."""
        from aiohttp import web

        entity = replica_id or dep
        return web.json_response(
            {"error": {"type": type(e).__name__, "deployment": dep,
                       "replica": replica_id, "retriable": True,
                       "detail": str(e) or repr(e),
                       "events": f"ray-tpu events --entity {entity}"}},
            status=503, headers={"Retry-After": "1"})

    @staticmethod
    def _stream_error_payload(dep: str, replica_id, e) -> dict:
        """Structured SSE error event: once streaming has begun the status
        line is gone, so mid-stream replica death is reported in-band —
        typed, naming the replica and its event-plane entity — instead of
        a bare repr the client can only string-match."""
        from ray_tpu.dag.push_stream import StreamSevered
        from ray_tpu.exceptions import ActorDiedError, WorkerCrashedError

        err = {"type": type(e).__name__, "deployment": dep,
               "detail": str(e) or repr(e)}
        if isinstance(e, (ActorDiedError, WorkerCrashedError,
                          StreamSevered)):
            # A severed/corrupted push-stream link is attributed like a
            # replica death: the client learns WHICH replica's stream was
            # lost and where its fate is recorded, and may retry.
            entity = replica_id or dep
            err["replica"] = replica_id
            err["retriable"] = True
            err["events"] = f"ray-tpu events --entity {entity}"
        return {"error": err}

    async def _handle(self, request):
        from aiohttp import web

        m = self._match(request.path)
        if m is None:
            return web.Response(status=404, text="no deployment matches path")
        _prefix, dep = m
        self._mint_request()
        admission = bool(CONFIG.serve_admission)
        # Stats requests bypass both the token bucket and the admission
        # queue: observability must stay readable exactly when the
        # deployment is saturated, or overloads can't be diagnosed.
        is_stats = (request.method == "GET"
                    and request.path.rstrip("/").endswith("/stats"))
        if admission and not is_stats:
            shed = self._bucket_shed(_prefix, dep)
            if shed is not None:
                return shed
        body = await request.read()
        # Trace root: an ingress request roots its own trace (head-based
        # RT_TRACE_SAMPLE; slow unsampled requests escalate via
        # RT_TRACE_SLOW_S in end_request). The context set here is copied
        # into the assign executor hop below, so the actor-call submit —
        # and everything downstream of the replica — chains under it.
        trh = _tracing.start_request(f"http {request.method} {request.path}")
        headers = dict(request.headers)
        tid = _tracing.request_trace_id(trh)
        if tid is not None:
            # Propagated in-band for deployments that want to tag logs /
            # downstream calls with the request's trace.
            headers["rt-trace-id"] = tid
        req = Request(method=request.method, path=request.path,
                      query=dict(request.query),
                      headers=headers, body=body)
        router = get_router(self.controller_name, dep)
        loop = asyncio.get_event_loop()
        # reference multiplex header: routes to a replica with the model hot.
        model_id = request.headers.get("serve_multiplexed_model_id", "")

        # Streaming requests (OpenAI-style {"stream": true} body or SSE
        # Accept header) ride the replica's streaming generator and are
        # written out as server-sent events as items arrive (reference
        # proxy.py streaming ASGI responses).
        want_stream = "text/event-stream" in request.headers.get("Accept", "")
        if not want_stream and body[:1] == b"{":
            try:
                want_stream = bool(json.loads(body).get("stream"))
            except Exception:
                want_stream = False
        if want_stream:
            try:
                return await self._handle_streaming(request, req, router,
                                                    model_id, loop)
            finally:
                _tracing.end_request(
                    trh, f"http {request.method} {request.path}",
                    {"deployment": dep, "stream": True})

        cancel = threading.Event() if admission else None
        meta: dict = {}

        async def _once():
            # Legacy path: assign only blocks when there are no replicas
            # (rare), so the default executor thread is held for
            # microseconds, not the request duration; the result await
            # costs no thread at all. Admission path: assign can park in
            # the bounded queue up to the deadline, so it rides the
            # dedicated pool and honors the client-disconnect cancel.
            # run_in_executor does NOT propagate contextvars (the trace
            # context, like the multiplexed id in replica.py): copy it in.
            pctx = contextvars.copy_context()
            if admission:
                fut = loop.run_in_executor(
                    self._pool(), lambda: pctx.run(
                        router.assign, "__call__", (req,), {},
                        multiplexed_model_id=model_id,
                        cancel=cancel, meta=meta,
                        bypass_queue=is_stats))
                try:
                    ref = await fut
                except asyncio.CancelledError:
                    # Client gone while (possibly) queued: release the
                    # queue slot; the parked thread notices within its
                    # 100ms poll. Consume the future's eventual
                    # QueueCancelled so it isn't logged as unretrieved.
                    cancel.set()
                    fut.add_done_callback(
                        lambda f: f.cancelled() or f.exception())
                    raise
            else:
                ref = await loop.run_in_executor(
                    None, lambda: pctx.run(
                        router.assign, "__call__", (req,), {},
                        multiplexed_model_id=model_id))
            return await self._resolver.submit(ref)

        try:
            if not admission:
                try:
                    result = await _once()
                except Exception as e:
                    from ray_tpu.exceptions import (
                        ActorDiedError,
                        WorkerCrashedError,
                    )

                    if isinstance(e, (ActorDiedError, WorkerCrashedError)):
                        # replica died mid-request: retry once on a survivor
                        try:
                            result = await _once()
                            return self._to_response(result)
                        except Exception as e2:  # noqa: F841
                            e = e2
                    logger.error("serve proxy error: %r", e)
                    return web.Response(status=500, text=repr(e))
                return self._to_response(result)
            from ray_tpu.exceptions import (
                ActorDiedError,
                BackPressureError,
                WorkerCrashedError,
            )

            try:
                retries = max(0, int(CONFIG.serve_retries))
                for attempt in range(retries + 1):
                    try:
                        result = await _once()
                        break
                    except (ActorDiedError, WorkerCrashedError):
                        # Replica died mid-request: jittered backoff, then
                        # re-admit against the survivors — until the
                        # per-request retry budget (RT_SERVE_RETRIES) runs
                        # out.
                        if attempt >= retries:
                            raise
                        await asyncio.sleep(_retry_pause_s(attempt))
                    except Exception as e:
                        # A replica-side concurrency-cap rejection (a race
                        # between routers) is retriable; real application
                        # errors are not. It crosses the wire wrapped in
                        # TaskError — unwrap so exhaustion still maps to
                        # 429, not 500.
                        if not _is_replica_busy(e):
                            raise
                        if attempt >= retries:
                            # Replica-raised: this router never counted it
                            # (its own slot view was free), so account the
                            # shed here before surfacing the 429.
                            router.record_shed("replica_busy")
                            cause = getattr(e, "cause", None)
                            raise cause if isinstance(
                                cause, BackPressureError) else e
                        await asyncio.sleep(_retry_pause_s(attempt))
                if is_stats and isinstance(result, dict):
                    serve_stats = router.admission_stats()
                    if serve_stats is not None:
                        result = dict(result)
                        peers = await self._peer_snapshots(dep)
                        if peers:
                            # Multi-proxy fleet: active-slot/queue counts
                            # are summed ACROSS proxies (each runs its own
                            # admission queue against the shared budgets)
                            # with a per-proxy breakdown alongside. A
                            # single-proxy response stays byte-identical —
                            # no peers, no extra keys.
                            import os as _os

                            agg = dict(serve_stats)
                            per = {self.proxy_id: dict(
                                serve_stats, pid=_os.getpid(),
                                active_streams=self._active_streams)}
                            for pname, snap in peers.items():
                                agg["queued"] += int(snap.get("queued", 0))
                                agg["shed_total"] += int(
                                    snap.get("shed_total", 0))
                                per[pname] = snap
                            result["serve"] = agg
                            result["serve_proxies"] = per
                        else:
                            result["serve"] = serve_stats
                return self._to_response(result)
            except BackPressureError as e:
                return self._shed_response(e)
            except (ActorDiedError, WorkerCrashedError) as e:
                logger.error("serve proxy error (replica death): %r", e)
                return self._death_response(dep, meta.get("replica_id"), e)
            except QueueCancelled:
                # Client disconnected while queued; the handler task is
                # normally cancelled before this surfaces — treat alike.
                raise asyncio.CancelledError()
            except Exception as e:
                logger.error("serve proxy error: %r", e)
                return web.Response(status=500, text=repr(e))
        finally:
            _tracing.end_request(trh, f"http {request.method} {request.path}",
                                 {"deployment": dep})

    @staticmethod
    def _sse_chunk(item) -> bytes:
        if isinstance(item, bytes):
            data = item.decode("utf-8", "replace")
        elif isinstance(item, str):
            data = item
        else:
            data = json.dumps(item)
        return f"data: {data}\n\n".encode()

    async def _stream_from_ring(self, resp, ring, gen, loop):
        """Token-ring reply path (README "Serving hot loop"): drain item
        batches from the transport — ONE reader wakeup and ONE socket
        flush per burst, however many tokens it carries — until the
        producer's end/err record. `ring` is either a shm StreamRing
        (same-host) or a PushStreamReader (cross-host); both speak the
        same read_batch contract. Replica death is detected via the
        stream task's completion ref, so a dead producer surfaces an
        attributed error within the resolver's poll cadence instead of
        hanging the SSE."""
        from ray_tpu.dag.push_stream import StreamSevered
        from ray_tpu.dag.stream import RingClosed

        cfut = self._resolver.submit(gen.completed())
        # Consume the exception if the response path never does (a stream
        # that ended via its "end" record before the death raced in).
        cfut.add_done_callback(
            lambda f: f.cancelled() or f.exception())
        completed_grace = False
        while True:
            try:
                batch = await loop.run_in_executor(
                    self._stream_pool,
                    lambda: ring.read_batch(timeout=0.25))
            except TimeoutError:
                if cfut.done():
                    exc = cfut.exception()
                    if exc is not None:
                        raise exc  # replica died mid-stream: attributed
                    if completed_grace:
                        # Task finished, ring drained, no end record (the
                        # producer was interrupted between its last item
                        # and the end marker): finish cleanly.
                        break
                    completed_grace = True
                continue
            except RingClosed:
                break
            except StreamSevered as sev:
                # The push link dropped (or lost a frame) mid-stream. If
                # the replica itself died, the completion ref knows within
                # its poll cadence — prefer that attribution; otherwise
                # surface the sever itself (also attributed, retriable).
                for _ in range(20):
                    if cfut.done():
                        exc = cfut.exception()
                        if exc is not None:
                            raise exc
                        break
                    await asyncio.sleep(0.25)
                try:
                    from ray_tpu._private.events import emit_event

                    emit_event(
                        "serve_stream_sever",
                        f"push-stream severed mid-SSE: {sev}",
                        entity=(self.proxy_id,))
                except Exception:
                    pass
                raise
            buf = bytearray()
            done = False
            for rec in batch:
                kind = rec[0]
                if kind == "item":
                    buf += self._sse_chunk(rec[1])
                elif kind == "end":
                    done = True
                elif kind == "err":
                    buf += self._sse_chunk({"error": rec[1]})
                    done = True
            if buf:
                await resp.write(bytes(buf))  # coalesced: one flush/burst
            if done:
                break
        await resp.write(b"data: [DONE]\n\n")
        await resp.write_eof()

    async def _handle_streaming(self, request, req, router, model_id, loop):
        """SSE response: one `data:` event per streamed item, then [DONE].
        With the token ring armed (RT_TOKEN_RING, default on) items ride a
        per-request shm StreamRing from the replica — one host hop per
        item BATCH — and multi-item arrivals coalesce into single socket
        flushes; RT_TOKEN_RING=0 keeps the classic one-ObjectRef-per-item
        reply path byte-identically."""
        from aiohttp import web

        ring = None
        ring_spec = None
        reader = None
        if CONFIG.token_ring and (
                loop.time() - self._ring_nak.get(router.deployment, -1e9)
                > 60.0):
            try:
                import uuid

                from ray_tpu.dag.stream import StreamRing

                # The pid in the name makes the segment attributable: a
                # proxy that dies mid-stream (SIGKILL) can't run its
                # unlink finally, so the next proxy to start sweeps ring
                # files whose creator pid is gone (_sweep_dead_rings).
                sid = f"sse_{os.getpid()}_{uuid.uuid4().hex[:12]}"
                ring = StreamRing(sid, int(CONFIG.token_ring_bytes))
                ring_spec = ring.spec()
            except Exception as e:
                logger.debug("token ring unavailable (%r): classic path", e)
                ring = None
                ring_spec = None
            if ring is not None and CONFIG.stream_push:
                # Offer the push-stream transport alongside the shm ring
                # (README "Cross-host streaming & multi-proxy"): a replica
                # that can't mmap our /dev/shm segment — it lives on
                # another host — dials back into this proxy's hub and
                # answers the handshake with "push" instead of "nak".
                try:
                    window = int(CONFIG.stream_window_bytes)
                    hub = await self._ensure_hub()
                    reader = hub.open(sid, window)
                    ring_spec["push"] = hub.spec(sid, window)
                except Exception as e:
                    logger.debug("push-stream hub unavailable (%r)", e)
                    reader = None
        admission = bool(CONFIG.serve_admission)
        cancel = threading.Event() if admission else None
        meta: dict = {}
        try:
            pctx = contextvars.copy_context()  # carry the trace context
            if admission:
                gen = await self._assign_stream(router, req, model_id,
                                                ring_spec, loop, pctx,
                                                cancel, meta)
            else:
                gen = await loop.run_in_executor(
                    None, lambda: pctx.run(
                        router.assign, "__call__", (req,), {},
                        multiplexed_model_id=model_id, streaming=True,
                        stream_ring=ring_spec))
        except asyncio.CancelledError:
            if ring is not None:
                ring.close(unlink=True)
            if reader is not None:
                reader.close()
            raise
        except Exception as e:
            if ring is not None:
                ring.close(unlink=True)
            if reader is not None:
                reader.close()
            if admission:
                from ray_tpu.exceptions import (
                    ActorDiedError,
                    BackPressureError,
                    WorkerCrashedError,
                )

                # The status line is still ours pre-stream: sheds and
                # replica death map to typed 429/503 rather than SSE.
                if isinstance(e, BackPressureError):
                    return self._shed_response(e)
                if isinstance(e, (ActorDiedError, WorkerCrashedError)):
                    logger.error(
                        "serve proxy stream error (replica death): %r", e)
                    return self._death_response(
                        router.deployment, meta.get("replica_id"), e)
            logger.error("serve proxy stream assign error: %r", e)
            return web.Response(status=500, text=repr(e))
        resp = web.StreamResponse(headers={
            "Content-Type": "text/event-stream",
            "Cache-Control": "no-cache",
            "Connection": "keep-alive"})
        await resp.prepare(request)
        self._pool()
        self._mint_stream(+1)
        it = iter(gen)
        sentinel = object()
        try:
            carry = None  # a first item the ring handshake pass consumed
            if ring is not None:
                # The replica's first generator item is the ring handshake
                # (ok = shm ring / push = rpc push-stream / nak). Anything
                # else means a producer that ignored the ring ask — fall
                # back and emit that item normally.
                ref = await loop.run_in_executor(
                    self._stream_pool, lambda: next(it, sentinel))
                first = (sentinel if ref is sentinel
                         else await self._resolver.submit(ref))
                if isinstance(first, dict) and "__rt_ring__" in first:
                    if first["__rt_ring__"] == "ok":
                        await self._stream_from_ring(resp, ring, gen, loop)
                        return resp
                    if first["__rt_ring__"] == "push" and reader is not None:
                        # Remote replica: same drain loop, fed by the hub
                        # reader (read_batch-compatible) instead of shm.
                        await self._stream_from_ring(resp, reader, gen,
                                                     loop)
                        return resp
                    self._ring_nak[router.deployment] = loop.time()
                elif first is not sentinel:
                    carry = first
            while True:
                if carry is not None:
                    item, carry = carry, None
                else:
                    # next() blocks until the replica reports the next
                    # item; keep the proxy loop free while waiting.
                    ref = await loop.run_in_executor(
                        self._stream_pool, lambda: next(it, sentinel))
                    if ref is sentinel:
                        break
                    item = await self._resolver.submit(ref)
                await resp.write(self._sse_chunk(item))
            await resp.write(b"data: [DONE]\n\n")
            await resp.write_eof()
        except Exception as e:
            # Client disconnects raise from resp.write: the tail writes
            # must not raise uncaught (they'd leak the stream below).
            logger.debug("serve proxy stream ended early: %r", e)
            try:
                if admission:
                    payload = self._stream_error_payload(
                        router.deployment, meta.get("replica_id"), e)
                else:
                    payload = {"error": repr(e)}
                await resp.write(
                    f"data: {json.dumps(payload)}\n\n".encode())
                await resp.write(b"data: [DONE]\n\n")
                await resp.write_eof()
            except Exception:
                pass
        finally:
            # Drop the generator NOW: its finalizer sends gen_close to the
            # replica, whose streaming wrapper closes the user iterator,
            # which releases the engine slot — without this, an abandoned
            # LLM stream keeps decoding to max_tokens for nobody.
            del it
            del gen
            if ring is not None:
                ring.close(unlink=True)
            if reader is not None:
                reader.close()
            self._mint_stream(-1)
        return resp

    async def _assign_stream(self, router, req, model_id, ring_spec, loop,
                             pctx, cancel, meta):
        """Admission-path streaming assign: rides the dedicated pool (it
        may park in the bounded queue up to the deadline), frees the queue
        slot if the client disconnects while waiting, and retries
        replica-busy races under the RT_SERVE_RETRIES budget."""
        retries = max(0, int(CONFIG.serve_retries))
        for attempt in range(retries + 1):
            fut = loop.run_in_executor(
                self._pool(), lambda: pctx.run(
                    router.assign, "__call__", (req,), {},
                    multiplexed_model_id=model_id, streaming=True,
                    stream_ring=ring_spec, cancel=cancel, meta=meta))
            try:
                return await fut
            except asyncio.CancelledError:
                cancel.set()
                fut.add_done_callback(
                    lambda f: f.cancelled() or f.exception())
                raise
            except Exception as e:
                from ray_tpu.exceptions import (
                    ActorDiedError,
                    WorkerCrashedError,
                )

                retriable = (isinstance(e, (ActorDiedError,
                                            WorkerCrashedError))
                             or _is_replica_busy(e))
                if not retriable or attempt >= retries:
                    raise
                await asyncio.sleep(_retry_pause_s(attempt))

    def _to_response(self, result):
        from aiohttp import web

        if isinstance(result, (dict, list)):
            return web.json_response(result)
        if isinstance(result, bytes):
            return web.Response(body=result,
                                content_type="application/octet-stream")
        if isinstance(result, web.Response):
            return result
        return web.Response(text=str(result))
