"""HTTP proxy: aiohttp server actor routing requests to deployments.

Parity target: reference python/ray/serve/_private/proxy.py:750 (ProxyActor
hosting an HTTP server per node; route table via long-poll; request ->
router -> replica; response assembly :1137). The server runs on the
replica actor's own asyncio loop (async actor), so request handling and
response awaits interleave without threads-per-request.
"""

from __future__ import annotations

import asyncio
import json
import logging
from typing import Optional

import ray_tpu
from ray_tpu.serve._private.replica import Request
from ray_tpu.serve._private.router import get_router, resolver_for

logger = logging.getLogger(__name__)


class Proxy:
    def __init__(self, controller_name: str, host: str = "127.0.0.1",
                 port: int = 8000):
        self.controller_name = controller_name
        self.host, self.port = host, port
        self.routes: dict[str, str] = {}
        self._version = -1
        self._site = None
        self._started = False
        self._resolver = None

    async def ready(self) -> int:
        """Bind the HTTP server; returns the bound port."""
        if self._started:
            return self.port
        from aiohttp import web

        app = web.Application()
        app.router.add_route("*", "/{tail:.*}", self._handle)
        runner = web.AppRunner(app, access_log=None)
        await runner.setup()
        site = web.TCPSite(runner, self.host, self.port)
        await site.start()
        self._site = site
        self._started = True
        self._resolver = resolver_for(asyncio.get_event_loop())
        # Populate the route table BEFORE declaring ready: serve.run
        # returns right after this, and the first request must not race
        # the initial long-poll to a 404.
        try:
            controller = ray_tpu.get_actor(self.controller_name)
            ref = controller.route_table.remote(-1, 0.0)
            rep = await asyncio.get_event_loop().run_in_executor(
                None, lambda r=ref: ray_tpu.get(r, timeout=10))
            self._version = rep["version"]
            self.routes = rep["routes"]
        except Exception as e:
            logger.warning("serve proxy initial route fetch failed: %r", e)
        asyncio.ensure_future(self._route_poll_loop())
        return self.port

    async def _route_poll_loop(self):
        while True:
            try:
                controller = ray_tpu.get_actor(self.controller_name)
                ref = controller.route_table.remote(self._version, 10.0)
                rep = await asyncio.get_event_loop().run_in_executor(
                    None, lambda r=ref: ray_tpu.get(r, timeout=15))
                self._version = rep["version"]
                self.routes = rep["routes"]
            except Exception as e:
                logger.debug("serve proxy route poll error: %r", e)
                await asyncio.sleep(0.2)

    def _match(self, path: str) -> Optional[tuple[str, str]]:
        best = None
        for prefix, dep in self.routes.items():
            norm = prefix.rstrip("/") or "/"
            if path == norm or path.startswith(norm + "/") or norm == "/":
                if best is None or len(norm) > len(best[0]):
                    best = (norm, dep)
        return best

    async def _handle(self, request):
        from aiohttp import web

        m = self._match(request.path)
        if m is None:
            return web.Response(status=404, text="no deployment matches path")
        _prefix, dep = m
        body = await request.read()
        req = Request(method=request.method, path=request.path,
                      query=dict(request.query),
                      headers=dict(request.headers), body=body)
        router = get_router(self.controller_name, dep)
        loop = asyncio.get_event_loop()
        # reference multiplex header: routes to a replica with the model hot.
        model_id = request.headers.get("serve_multiplexed_model_id", "")

        async def _once():
            # assign only blocks when there are no replicas (rare), so the
            # executor thread is held for microseconds, not the request
            # duration; the result await costs no thread at all.
            ref = await loop.run_in_executor(
                None, lambda: router.assign("__call__", (req,), {},
                                            multiplexed_model_id=model_id))
            return await self._resolver.submit(ref)

        try:
            result = await _once()
        except Exception as e:
            from ray_tpu.exceptions import ActorDiedError, WorkerCrashedError

            if isinstance(e, (ActorDiedError, WorkerCrashedError)):
                # replica died mid-request: retry once on a survivor
                try:
                    result = await _once()
                    return self._to_response(result)
                except Exception as e2:  # noqa: F841
                    e = e2
            logger.error("serve proxy error: %r", e)
            return web.Response(status=500, text=repr(e))
        return self._to_response(result)

    def _to_response(self, result):
        from aiohttp import web

        if isinstance(result, (dict, list)):
            return web.json_response(result)
        if isinstance(result, bytes):
            return web.Response(body=result,
                                content_type="application/octet-stream")
        if isinstance(result, web.Response):
            return result
        return web.Response(text=str(result))
