"""ray_tpu.serve — scalable model serving on the cluster runtime.

Parity target: reference python/ray/serve (deployment decorator + .bind
application graphs, serve.run, DeploymentHandle composition, @serve.batch,
autoscaling, HTTP ingress). The serving half of the TPU-era value
proposition: replicas are async actors whose event loops interleave
requests, the controller reconciles declared state, and routing uses
power-of-two-choices over long-polled membership.
"""

from __future__ import annotations

import asyncio
import functools
import hashlib
import pickle
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Optional

import ray_tpu
from ray_tpu.serve._private.controller import (
    CONTROLLER_NAME,
    PROXY_NAME,
    ServeController,
)
from ray_tpu.serve._private.replica import Request
from ray_tpu.serve._private.router import (
    DeploymentHandle,
    DeploymentResponse,
    reset_routers,
)

__all__ = [
    "Application",
    "Deployment",
    "DeploymentHandle",
    "DeploymentResponse",
    "Request",
    "batch",
    "delete",
    "get_multiplexed_model_id",
    "multiplexed",
    "deployment",
    "get_app_handle",
    "get_deployment_handle",
    "proxy_ports",
    "run",
    "shutdown",
    "status",
]


@dataclass
class Application:
    """A bound deployment (+ its bound argument subgraph) — reference
    serve built-application graphs (Deployment.bind)."""

    deployment: "Deployment"
    args: tuple = ()
    kwargs: dict = field(default_factory=dict)


class Deployment:
    def __init__(self, func_or_class, name: str, num_replicas=1,
                 ray_actor_options: Optional[dict] = None,
                 max_ongoing_requests: int = 16,
                 autoscaling_config: Optional[dict] = None,
                 version: Optional[str] = None,
                 max_queued_requests: int = -1,
                 queue_deadline_s: Optional[float] = None):
        self._func_or_class = func_or_class
        self.name = name
        self.num_replicas = num_replicas
        self.ray_actor_options = ray_actor_options
        self.max_ongoing_requests = max_ongoing_requests
        self.autoscaling_config = autoscaling_config
        self.version = version
        # Admission budgets (README "Overload & admission control"):
        # max_queued_requests bounds the per-router queue behind the
        # replicas' concurrency caps (-1 = unbounded, the deadline still
        # sheds); queue_deadline_s caps how long a request may wait for a
        # slot before it is shed (None = RT_SERVE_QUEUE_DEADLINE_S).
        self.max_queued_requests = max_queued_requests
        self.queue_deadline_s = queue_deadline_s

    def options(self, **overrides) -> "Deployment":
        cfg = dict(
            name=self.name, num_replicas=self.num_replicas,
            ray_actor_options=self.ray_actor_options,
            max_ongoing_requests=self.max_ongoing_requests,
            autoscaling_config=self.autoscaling_config, version=self.version,
            max_queued_requests=self.max_queued_requests,
            queue_deadline_s=self.queue_deadline_s)
        cfg.update(overrides)
        return Deployment(self._func_or_class, **cfg)

    def bind(self, *args, **kwargs) -> Application:
        return Application(self, args, kwargs)

    def _spec(self, route_prefix: Optional[str], args: tuple,
              kwargs: dict) -> dict:
        import cloudpickle

        version = self.version or hashlib.sha1(
            cloudpickle.dumps(self._func_or_class)).hexdigest()[:12]
        num_replicas = self.num_replicas
        autoscaling = self.autoscaling_config
        if num_replicas == "auto" and autoscaling is None:
            autoscaling = {"min_replicas": 1, "max_replicas": 4,
                           "target_ongoing_requests": 2}
        return {
            "name": self.name,
            "callable": self._func_or_class,
            "init_args": args,
            "init_kwargs": kwargs,
            "num_replicas": 1 if num_replicas == "auto" else num_replicas,
            "autoscaling_config": autoscaling,
            "ray_actor_options": self.ray_actor_options,
            "max_ongoing_requests": self.max_ongoing_requests,
            "max_queued_requests": self.max_queued_requests,
            "queue_deadline_s": self.queue_deadline_s,
            "route_prefix": route_prefix,
            "version": version,
        }


def deployment(_func_or_class=None, *, name: Optional[str] = None,
               num_replicas=1, ray_actor_options: Optional[dict] = None,
               max_ongoing_requests: int = 16,
               autoscaling_config: Optional[dict] = None,
               version: Optional[str] = None,
               max_queued_requests: int = -1,
               queue_deadline_s: Optional[float] = None):
    """@serve.deployment (reference api.py:deployment)."""

    def wrap(fc):
        return Deployment(fc, name or fc.__name__, num_replicas,
                          ray_actor_options, max_ongoing_requests,
                          autoscaling_config, version,
                          max_queued_requests, queue_deadline_s)

    if _func_or_class is not None:
        return wrap(_func_or_class)
    return wrap


# ------------------------------------------------------------------ control
def _get_or_create_controller():
    wrapped = ray_tpu.remote(num_cpus=0, max_concurrency=64)(ServeController)
    return wrapped.options(name=CONTROLLER_NAME, lifetime="detached",
                           get_if_exists=True).remote()


def _deploy_app(app: Application, controller, route_prefix: Optional[str],
                seen: dict) -> str:
    """Deploy `app` and (recursively) every Application bound into its
    args, replacing them with DeploymentHandles (model composition —
    reference build_app / handle injection)."""

    def resolve(v):
        if isinstance(v, Application):
            dep_name = _deploy_app(v, controller, None, seen)
            return DeploymentHandle(dep_name, CONTROLLER_NAME)
        return v

    if id(app) in seen:
        return seen[id(app)]
    args = tuple(resolve(a) for a in app.args)
    kwargs = {k: resolve(v) for k, v in app.kwargs.items()}
    spec = app.deployment._spec(route_prefix, args, kwargs)
    ray_tpu.get(controller.deploy.remote(spec), timeout=30)
    seen[id(app)] = spec["name"]
    return spec["name"]


def run(target: Application, *, route_prefix: str = "/",
        host: str = "127.0.0.1", port: int = 8000,
        grpc_port: Optional[int] = None, num_proxies: Optional[int] = None,
        _blocking: bool = True, timeout_s: float = 60.0) -> DeploymentHandle:
    """Deploy an application and start the HTTP ingress (reference
    serve/api.py:run). grpc_port (0 = auto-pick) additionally starts the
    gRPC ingress (reference gRPCProxy, proxy.py:530): unary calls at
    /ray_tpu.serve.<deployment>/<method>, server streaming with the
    'Stream' method suffix.

    num_proxies (default RT_SERVE_PROXIES, normally 1) fans the HTTP
    ingress out across N proxy processes: proxy 0 keeps the requested
    `port` (and the classic PROXY_NAME, so single-proxy behavior is
    unchanged), extras auto-bind free ports discoverable via
    serve.proxy_ports(). Each proxy runs its own admission queues against
    the same controller-published budgets — the replica-side concurrency
    cap is the shared backstop (README "Cross-host streaming &
    multi-proxy")."""
    from ray_tpu._private.rtconfig import CONFIG

    if not isinstance(target, Application):
        raise TypeError("serve.run expects Deployment.bind(...)")
    if num_proxies is None:
        num_proxies = int(CONFIG.serve_proxies)
    num_proxies = max(1, num_proxies)
    controller = _get_or_create_controller()
    ingress = _deploy_app(target, controller, route_prefix, {})
    # HTTP proxy fleet (reference runs one per node).
    from ray_tpu.serve._private.proxy import Proxy

    proxy_cls = ray_tpu.remote(num_cpus=0, max_concurrency=64)(Proxy)
    proxies = []
    for i in range(num_proxies):
        name = PROXY_NAME if i == 0 else f"{PROXY_NAME}_{i}"
        proxies.append(proxy_cls.options(
            name=name, lifetime="detached", get_if_exists=True).remote(
            CONTROLLER_NAME, host, port if i == 0 else 0,
            grpc_port if i == 0 else None, proxy_id=name))
    for proxy in proxies:
        ray_tpu.get(proxy.ready.remote(), timeout=30)
    if grpc_port is not None:
        # The proxy may predate this run (get_if_exists reuses it with the
        # FIRST run's constructor args): start the ingress in-place.
        ray_tpu.get(proxies[0].ensure_grpc.remote(grpc_port), timeout=30)
    if _blocking:
        deadline = time.monotonic() + timeout_s
        st: dict = {}
        while time.monotonic() < deadline:
            st = ray_tpu.get(controller.status.remote(), timeout=10)
            if all(d["status"] == "RUNNING" for d in st.values()):
                break
            time.sleep(0.1)
        else:
            raise TimeoutError(f"deployments not ready after {timeout_s}s: {st}")
    return DeploymentHandle(ingress, CONTROLLER_NAME)


def get_grpc_port() -> Optional[int]:
    """Bound gRPC ingress port of the running proxy (None if disabled)."""
    proxy = ray_tpu.get_actor(PROXY_NAME)
    return ray_tpu.get(proxy.grpc_ready.remote(), timeout=10)


def proxy_ports() -> dict:
    """proxy_id -> bound HTTP port for every proxy registered with the
    controller. With num_proxies=1 this is {PROXY_NAME: port}; with a
    fleet, clients (or an external load balancer) spread connections
    across the returned ports."""
    controller = ray_tpu.get_actor(CONTROLLER_NAME)
    reg = ray_tpu.get(controller.list_proxies.remote(), timeout=10)
    return {pid: info["port"] for pid, info in reg.items()}


def status() -> dict:
    controller = ray_tpu.get_actor(CONTROLLER_NAME)
    return ray_tpu.get(controller.status.remote(), timeout=10)


def get_deployment_handle(deployment_name: str, app_name: str = "") -> DeploymentHandle:
    return DeploymentHandle(deployment_name, CONTROLLER_NAME)


get_app_handle = get_deployment_handle


def delete(name: str):
    controller = ray_tpu.get_actor(CONTROLLER_NAME)
    ray_tpu.get(controller.delete.remote(name), timeout=30)


def shutdown():
    """Tear down all deployments, every registered proxy, and the
    controller."""
    try:
        controller = ray_tpu.get_actor(CONTROLLER_NAME)
    except Exception:
        reset_routers()
        return
    proxy_names = [PROXY_NAME]
    try:
        reg = ray_tpu.get(controller.list_proxies.remote(), timeout=10)
        proxy_names += [p for p in reg if p != PROXY_NAME]
    except Exception:
        pass
    try:
        ray_tpu.get(controller.shutdown_all.remote(), timeout=30)
    except Exception:
        pass
    for name in (*proxy_names, CONTROLLER_NAME):
        try:
            ray_tpu.kill(ray_tpu.get_actor(name))
        except Exception:
            pass
    reset_routers()


# ------------------------------------------------------------------- batch
def batch(_func=None, *, max_batch_size: int = 8,
          batch_wait_timeout_s: float = 0.01):
    """@serve.batch (reference serve/batching.py): concurrent calls to the
    wrapped async method are buffered and delivered as ONE call with a list
    argument; each caller gets its element of the returned list. The
    batch-inference pattern for the MXU: many small requests fuse into one
    large matmul-shaped call."""

    def wrap(func):
        state_attr = f"__serve_batch_{func.__name__}"

        @functools.wraps(func)
        async def wrapper(self, item):
            # Everything here runs on ONE event loop (the replica's), so the
            # queue/drainer handoff needs no locks: a coroutine can only be
            # interleaved at its awaits.
            st = getattr(self, state_attr, None)
            if st is None:
                st = {"queue": [], "wake": asyncio.Event(), "drainer": None}
                setattr(self, state_attr, st)
            fut = asyncio.get_event_loop().create_future()
            st["queue"].append((item, fut))
            if len(st["queue"]) >= max_batch_size:
                st["wake"].set()
            if st["drainer"] is None or st["drainer"].done():
                st["drainer"] = asyncio.ensure_future(_drain(self, st))
            return await fut

        async def _drain(self_obj, st):
            """Lives while there is work; flushes one batch per round. A
            batch in flight is never cancelled, and items arriving during a
            flush are picked up by the next round (the while-check and the
            task's completion are atomic w.r.t. the loop, so wrapper's
            done()-check can't miss work)."""
            while st["queue"]:
                st["wake"] = asyncio.Event()
                if len(st["queue"]) < max_batch_size:
                    try:
                        await asyncio.wait_for(st["wake"].wait(),
                                               timeout=batch_wait_timeout_s)
                    except asyncio.TimeoutError:
                        pass
                batch = st["queue"][:max_batch_size]
                st["queue"] = st["queue"][max_batch_size:]
                try:
                    outs = await func(self_obj, [b[0] for b in batch])
                    if len(outs) != len(batch):
                        raise ValueError(
                            f"@serve.batch function returned {len(outs)} "
                            f"results for {len(batch)} inputs")
                    for (_i, fut), out in zip(batch, outs):
                        if not fut.done():
                            fut.set_result(out)
                except Exception as e:
                    for _i, fut in batch:
                        if not fut.done():
                            fut.set_exception(e)

        return wrapper

    if _func is not None:
        return wrap(_func)
    return wrap


# --------------------------------------------------------------- multiplex
def get_multiplexed_model_id() -> str:
    """Model id of the request currently being handled (reference
    serve.get_multiplexed_model_id) — set by handle.options(
    multiplexed_model_id=...) or the `serve_multiplexed_model_id` HTTP
    header."""
    from ray_tpu.serve._private.replica import _multiplexed_model_id

    return _multiplexed_model_id.get()


def multiplexed(_func=None, *, max_num_models_per_replica: int = 3):
    """Decorator for a deployment's model-loader method (reference
    serve/multiplex.py @serve.multiplexed): caches up to
    `max_num_models_per_replica` loaded models per replica with LRU
    eviction, so one replica pool serves many fine-tuned model variants.

    Usage::

        @serve.deployment
        class Multi:
            @serve.multiplexed(max_num_models_per_replica=2)
            async def get_model(self, model_id: str):
                return load_model(model_id)

            async def __call__(self, request):
                model = await self.get_model(serve.get_multiplexed_model_id())
                return model.predict(request.json())
    """

    def deco(fn):
        cache_attr = f"__rt_mux_cache_{fn.__name__}"
        is_coro = asyncio.iscoroutinefunction(fn)

        async def _load(self, model_id: str):
            # Replica requests interleave on ONE event loop; the cache maps
            # model_id -> Future so concurrent requests for the same model
            # await a single in-flight load instead of double-loading.
            # Eviction pops the reference and lets GC reclaim the model once
            # the last in-flight request drops it (calling a release hook
            # here would tear down a model another request is still using).
            cache = getattr(self, cache_attr, None)
            if cache is None:
                cache = {}
                setattr(self, cache_attr, cache)
            fut = cache.get(model_id)
            if fut is not None:
                cache[model_id] = cache.pop(model_id)  # LRU touch
                return await asyncio.shield(fut)
            loop = asyncio.get_event_loop()
            fut = loop.create_future()
            cache[model_id] = fut
            try:
                if is_coro:
                    model = await fn(self, model_id)
                else:
                    # A sync loader must not freeze the replica's event loop
                    # for the duration of a model load.
                    model = await loop.run_in_executor(
                        None, functools.partial(fn, self, model_id))
            except BaseException as e:
                cache.pop(model_id, None)
                if not fut.done():
                    fut.set_exception(e)
                    # consumed by any concurrent waiter; don't warn if not
                    fut.exception()
                raise
            fut.set_result(model)
            while len(cache) > max_num_models_per_replica:
                for mid in list(cache):
                    if mid != model_id and cache[mid].done():
                        del cache[mid]
                        break
                else:
                    break  # everything else still loading: nothing to evict
            return model

        @functools.wraps(fn)
        async def wrapper(self, model_id: str):
            return await _load(self, model_id)

        wrapper.__rt_multiplexed__ = True
        return wrapper

    if _func is not None:
        return deco(_func)
    return deco
