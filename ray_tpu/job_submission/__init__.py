"""Job submission SDK.

Parity target: reference python/ray/job_submission (JobSubmissionClient,
JobStatus) backed by the dashboard job manager
(dashboard/modules/job/job_manager.py:60, submit_job:423). Here the
controller owns the job table and a node agent runs the entrypoint as a
driver subprocess with `RT_ADDRESS` injected so `ray_tpu.init()` inside the
job attaches to the same cluster.
"""

from __future__ import annotations

import os
import time
from typing import Iterator, Optional

from ray_tpu._private import rpc


class JobStatus:
    PENDING = "PENDING"
    RUNNING = "RUNNING"
    SUCCEEDED = "SUCCEEDED"
    FAILED = "FAILED"
    STOPPED = "STOPPED"

    TERMINAL = frozenset({SUCCEEDED, FAILED, STOPPED})

    @classmethod
    def is_terminal(cls, status: str) -> bool:
        return status in cls.TERMINAL


class JobInfo(dict):
    """Dict view of a job table row (submission_id, entrypoint, status,
    message, node_id, start_time, end_time, metadata, runtime_env)."""

    @property
    def status(self) -> str:
        return self["status"]

    @property
    def submission_id(self) -> str:
        return self["submission_id"]


class JobSubmissionClient:
    """Submit and manage driver jobs against a running cluster.

    `address` is "host:port" of the controller (what `ray-tpu start --head`
    prints); defaults to $RT_ADDRESS, then to the current driver's cluster
    when `ray_tpu.init()` already ran in this process.
    """

    def __init__(self, address: Optional[str] = None):
        if address is None:
            address = os.environ.get("RT_ADDRESS")
        if address is None:
            from ray_tpu._private.worker import global_worker

            w = global_worker()
            if w is not None:
                address = f"{w.controller_addr[0]}:{w.controller_addr[1]}"
        if address is None:
            raise ValueError("no address: pass one, set RT_ADDRESS, or init() first")
        host, port = address.rsplit(":", 1)
        self._addr = (host, int(port))
        self._io = rpc.EventLoopThread(name="job-client")
        self._conn: Optional[rpc.Connection] = None

    def _call(self, method: str, timeout: float = 30.0, **kw):
        async def _go():
            if self._conn is None or self._conn.closed:
                self._conn = await rpc.connect(*self._addr)
                await self._conn.call("register", kind="client",
                                      worker_id=f"jobclient-{os.getpid()}",
                                      address=None)
            return await self._conn.call(method, **kw)

        return self._io.run(_go(), timeout=timeout)

    # ------------------------------------------------------------- API
    def submit_job(self, *, entrypoint: str, submission_id: Optional[str] = None,
                   runtime_env: Optional[dict] = None,
                   metadata: Optional[dict] = None) -> str:
        rep = self._call("submit_job", entrypoint=entrypoint,
                         submission_id=submission_id, runtime_env=runtime_env,
                         metadata=metadata)
        return rep["submission_id"]

    def get_job_status(self, submission_id: str) -> str:
        return self._call("get_job", submission_id=submission_id)["job"]["status"]

    def get_job_info(self, submission_id: str) -> JobInfo:
        return JobInfo(self._call("get_job", submission_id=submission_id)["job"])

    def list_jobs(self) -> list[JobInfo]:
        return [JobInfo(j) for j in self._call("list_jobs")["jobs"]]

    def stop_job(self, submission_id: str) -> bool:
        return bool(self._call("stop_job", submission_id=submission_id)["stopped"])

    def _read_logs_from(self, submission_id: str, offset: int) -> tuple[bytes, int]:
        """Read to EOF. The agent caps each reply (JOB_LOG_CHUNK_BYTES) and
        marks clipped ones `truncated: true`; loop on the marker so a large
        log arrives whole without ever riding one unbounded RPC frame."""
        chunks = []
        while True:
            rep = self._call("job_logs", submission_id=submission_id, offset=offset)
            data = bytes(rep["data"])
            offset = rep["offset"]
            if data:
                chunks.append(data)
            if not rep.get("truncated", bool(data)):
                # Marker-less legacy replies fall back to read-until-empty.
                return b"".join(chunks), offset

    def get_job_logs(self, submission_id: str) -> str:
        data, _ = self._read_logs_from(submission_id, 0)
        return data.decode(errors="replace")

    def tail_job_logs(self, submission_id: str,
                      poll_interval_s: float = 0.25) -> Iterator[str]:
        """Yield log chunks until the job reaches a terminal state."""
        offset = 0
        while True:
            data, offset = self._read_logs_from(submission_id, offset)
            if data:
                yield data.decode(errors="replace")
            status = self.get_job_status(submission_id)
            if JobStatus.is_terminal(status):
                tail, offset = self._read_logs_from(submission_id, offset)
                if tail:
                    yield tail.decode(errors="replace")
                return
            time.sleep(poll_interval_s)

    def wait_until_finished(self, submission_id: str, timeout: float = 300.0,
                            poll_interval_s: float = 0.2) -> str:
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            status = self.get_job_status(submission_id)
            if JobStatus.is_terminal(status):
                return status
            time.sleep(poll_interval_s)
        raise TimeoutError(f"job {submission_id} still running after {timeout}s")

    def close(self):
        if self._conn is not None:
            conn = self._conn

            async def _bye():
                await conn.close()

            try:
                self._io.run(_bye(), timeout=5)
            except Exception:
                pass
        self._io.stop()
