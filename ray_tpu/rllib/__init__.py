"""ray_tpu.rllib — reinforcement learning on the cluster runtime.

Parity target: reference rllib/ new API stack (Algorithm / AlgorithmConfig,
RLModule, Learner, EnvRunner/EnvRunnerGroup). JAX-native: the policy is a
flax module, the PPO update is one compiled program (all epochs/minibatches
inside lax.scan), rollouts run on parallel env-runner actors with numpy
vector envs.
"""

from ray_tpu.rllib.algorithm import (
    Algorithm,
    AlgorithmConfig,
    EnvRunnerGroup,
    PPO,
    PPOConfig,
)
from ray_tpu.rllib.dqn import DQN, DQNConfig, DQNEnvRunner, DQNLearner, DQNLearnerConfig
from ray_tpu.rllib.env import ENV_REGISTRY, CartPoleVecEnv, make_vec_env
from ray_tpu.rllib.impala import IMPALA, IMPALAConfig, IMPALALearner, IMPALALearnerConfig
from ray_tpu.rllib.env_runner import SingleAgentEnvRunner
from ray_tpu.rllib.learner import PPOLearner, PPOLearnerConfig, compute_gae
from ray_tpu.rllib.multi_agent import (
    MultiAgentCartPole,
    MultiAgentEnvRunner,
    MultiAgentPPO,
    MultiAgentPPOConfig,
)
from ray_tpu.rllib.replay import PrioritizedReplayBuffer, ReplayBufferGroup
from ray_tpu.rllib.rl_module import RLModule, RLModuleSpec

__all__ = [
    "Algorithm",
    "AlgorithmConfig",
    "CartPoleVecEnv",
    "DQN",
    "DQNConfig",
    "DQNEnvRunner",
    "DQNLearner",
    "DQNLearnerConfig",
    "ENV_REGISTRY",
    "EnvRunnerGroup",
    "IMPALA",
    "IMPALAConfig",
    "IMPALALearner",
    "IMPALALearnerConfig",
    "MultiAgentCartPole",
    "MultiAgentEnvRunner",
    "MultiAgentPPO",
    "MultiAgentPPOConfig",
    "PPO",
    "PPOConfig",
    "PPOLearner",
    "PPOLearnerConfig",
    "PrioritizedReplayBuffer",
    "ReplayBufferGroup",
    "RLModule",
    "RLModuleSpec",
    "SingleAgentEnvRunner",
    "compute_gae",
    "make_vec_env",
]
