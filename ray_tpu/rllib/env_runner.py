"""EnvRunner: the rollout actor.

Parity target: reference rllib/env/single_agent_env_runner.py:68 +
env_runner_group.py:71 — a fleet of actors each stepping a vectorized env
with the current policy, returning sample batches; weights broadcast each
iteration.
"""

from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp

from ray_tpu.rllib.env import make_vec_env
from ray_tpu.rllib.rl_module import RLModule, RLModuleSpec


class SingleAgentEnvRunner:
    """Wrapped with ray_tpu.remote by EnvRunnerGroup (so per-runner
    resources can be attached)."""

    def __init__(self, env_name, num_envs: int, module_spec: RLModuleSpec,
                 seed: int = 0):
        self.env = make_vec_env(env_name, num_envs, seed=seed)
        self.module = RLModule(module_spec)
        self.params = None
        self._rng = jax.random.PRNGKey(seed)
        self._explore = jax.jit(self.module.forward_exploration)
        self.obs = self.env.obs()
        # episode-return bookkeeping (reference metrics: episode_return_mean)
        self._ep_ret = np.zeros(num_envs, dtype=np.float64)
        self._done_returns: list[float] = []

    def set_weights(self, weights):
        self.params = weights
        return True

    def sample(self, num_steps: int) -> dict:
        """Roll out num_steps per env with the CURRENT weights. Returns a
        [T, N, ...] batch (numpy) + rollout metrics."""
        assert self.params is not None, "set_weights first"
        T, N = num_steps, self.env.num_envs
        obs_buf = np.zeros((T, N, self.env.observation_dim), np.float32)
        act_buf = np.zeros((T, N), np.int32)
        logp_buf = np.zeros((T, N), np.float32)
        val_buf = np.zeros((T, N), np.float32)
        rew_buf = np.zeros((T, N), np.float32)
        done_buf = np.zeros((T, N), np.float32)
        for t in range(T):
            self._rng, sub = jax.random.split(self._rng)
            action, logp, value = self._explore(
                self.params, jnp.asarray(self.obs), sub)
            action = np.asarray(action)
            obs_buf[t] = self.obs
            act_buf[t] = action
            logp_buf[t] = np.asarray(logp)
            val_buf[t] = np.asarray(value)
            self.obs, rewards, dones = self.env.step(action)
            rew_buf[t] = rewards
            done_buf[t] = dones
            self._ep_ret += rewards
            finished = dones.astype(bool)
            if finished.any():
                self._done_returns.extend(self._ep_ret[finished].tolist())
                self._ep_ret[finished] = 0.0
        _, last_values = self.module.forward_train(
            self.params, jnp.asarray(self.obs))
        returns, self._done_returns = self._done_returns, []
        return {
            "obs": obs_buf, "actions": act_buf, "logp_old": logp_buf,
            "values": val_buf, "rewards": rew_buf, "dones": done_buf,
            "last_values": np.asarray(last_values),
            # Bootstrap observation for off-policy learners (IMPALA's
            # V-trace re-evaluates it under the CURRENT params).
            "last_obs": np.asarray(self.obs, dtype=np.float32),
            "episode_returns": returns,
        }
