"""DQN: off-policy Q-learning with double-Q targets + prioritized replay.

Parity target: reference rllib/algorithms/dqn/dqn.py (new API stack:
EnvRunners collect with epsilon-greedy, transitions land in a prioritized
replay buffer, the learner samples minibatches, double-DQN targets, target
net synced every `target_network_update_freq` steps, TD errors fed back as
priorities). TPU-native: the whole update (forward, huber loss, Adam,
target sync) is ONE jitted function; the buffer fleet stays on CPU hosts.
"""

from __future__ import annotations

import copy
from dataclasses import dataclass, field, replace
from typing import Any, Optional

import numpy as np

import ray_tpu
from ray_tpu.rllib.algorithm import Algorithm, AlgorithmConfig
from ray_tpu.rllib.env import make_vec_env
from ray_tpu.rllib.replay import ReplayBufferGroup
from ray_tpu.rllib.rl_module import RLModuleSpec

import flax.linen as nn
import jax
import jax.numpy as jnp
import optax


class QNet(nn.Module):
    spec: RLModuleSpec

    @nn.compact
    def __call__(self, obs):
        x = obs
        for i, h in enumerate(self.spec.hidden):
            x = nn.relu(nn.Dense(h, name=f"fc{i}")(x))
        return nn.Dense(self.spec.action_dim, name="q")(x)


@dataclass
class DQNLearnerConfig:
    lr: float = 1e-3
    gamma: float = 0.99
    target_update_freq: int = 100  # learner updates between target syncs
    huber_delta: float = 1.0


class DQNLearner:
    """Double-DQN learner: one jitted update step (reference
    dqn_rainbow_torch_learner compute_loss_for_module)."""

    def __init__(self, spec: RLModuleSpec, cfg: DQNLearnerConfig, seed=0):
        self.cfg = cfg
        self.net = QNet(spec)
        dummy = jnp.zeros((1, spec.observation_dim), jnp.float32)
        self.params = self.net.init(jax.random.PRNGKey(seed), dummy)
        self.target_params = jax.tree.map(lambda x: x, self.params)
        self.opt = optax.adam(cfg.lr)
        self.opt_state = self.opt.init(self.params)
        self._updates = 0

        def loss_fn(params, target_params, batch, weights):
            q = self.net.apply(params, batch["obs"])  # [B, A]
            q_sa = jnp.take_along_axis(
                q, batch["actions"][:, None], axis=-1)[:, 0]
            # Double DQN: online net picks a', target net evaluates it.
            next_q_online = self.net.apply(params, batch["next_obs"])
            next_a = jnp.argmax(next_q_online, axis=-1)
            next_q_target = self.net.apply(target_params, batch["next_obs"])
            next_v = jnp.take_along_axis(
                next_q_target, next_a[:, None], axis=-1)[:, 0]
            target = batch["rewards"] + cfg.gamma * (
                1.0 - batch["dones"]) * next_v
            td = q_sa - jax.lax.stop_gradient(target)
            loss = jnp.mean(weights * optax.huber_loss(
                td, delta=cfg.huber_delta))
            return loss, td

        def update(params, target_params, opt_state, batch, weights):
            (loss, td), grads = jax.value_and_grad(
                loss_fn, has_aux=True)(params, target_params, batch, weights)
            updates, opt_state = self.opt.update(grads, opt_state, params)
            params = optax.apply_updates(params, updates)
            return params, opt_state, loss, td

        self._update = jax.jit(update)

    def update(self, batch: dict, weights: np.ndarray):
        """-> (stats, |td| per sample for priority feedback)."""
        jbatch = {
            "obs": jnp.asarray(batch["obs"], jnp.float32),
            "actions": jnp.asarray(batch["actions"], jnp.int32),
            "rewards": jnp.asarray(batch["rewards"], jnp.float32),
            "next_obs": jnp.asarray(batch["next_obs"], jnp.float32),
            "dones": jnp.asarray(batch["dones"], jnp.float32),
        }
        self.params, self.opt_state, loss, td = self._update(
            self.params, self.target_params, self.opt_state, jbatch,
            jnp.asarray(weights, jnp.float32))
        self._updates += 1
        if self._updates % self.cfg.target_update_freq == 0:
            self.target_params = jax.tree.map(lambda x: x, self.params)
        return ({"loss": float(loss), "num_updates": self._updates},
                np.abs(np.asarray(td)))

    def get_weights(self):
        return self.params


class DQNEnvRunner:
    """Epsilon-greedy rollout actor emitting TRANSITIONS (off-policy: the
    batch is (s, a, r, s', done) tuples, not trajectories). Reference
    single_agent_env_runner with the epsilon-greedy exploration connector."""

    def __init__(self, env_name, num_envs: int, spec: RLModuleSpec, seed=0):
        self.env = make_vec_env(env_name, num_envs, seed=seed)
        self.net = QNet(spec)
        self.params = None
        self._rng = np.random.RandomState(seed)
        self._q = jax.jit(self.net.apply)
        self.obs = self.env.obs()
        self._ep_ret = np.zeros(num_envs, np.float64)
        self._done_returns: list[float] = []

    def set_weights(self, weights):
        self.params = weights
        return True

    def sample(self, num_steps: int, epsilon: float) -> dict:
        assert self.params is not None
        N = self.env.num_envs
        obs_b, act_b, rew_b, next_b, done_b = [], [], [], [], []
        for _ in range(num_steps):
            q = np.asarray(self._q(self.params, jnp.asarray(self.obs)))
            greedy = q.argmax(axis=-1)
            rand = self._rng.randint(0, q.shape[-1], size=N)
            explore = self._rng.random_sample(N) < epsilon
            action = np.where(explore, rand, greedy).astype(np.int64)
            obs_b.append(self.obs.copy())
            self.obs, rewards, dones = self.env.step(action)
            act_b.append(action)
            rew_b.append(rewards)
            next_b.append(self.obs.copy())
            done_b.append(dones)
            self._ep_ret += rewards
            fin = dones.astype(bool)
            if fin.any():
                self._done_returns.extend(self._ep_ret[fin].tolist())
                self._ep_ret[fin] = 0.0
        returns, self._done_returns = self._done_returns, []
        return {
            "obs": np.concatenate(obs_b).astype(np.float32),
            "actions": np.concatenate(act_b).astype(np.int32),
            "rewards": np.concatenate(rew_b).astype(np.float32),
            "next_obs": np.concatenate(next_b).astype(np.float32),
            "dones": np.concatenate(done_b).astype(np.float32),
            "episode_returns": returns,
        }


@dataclass
class DQNConfig(AlgorithmConfig):
    learner: DQNLearnerConfig = field(default_factory=DQNLearnerConfig)
    replay_capacity: int = 50_000
    replay_shards: int = 1
    replay_alpha: float = 0.6
    replay_beta: float = 0.4
    train_batch_size: int = 64
    num_learner_updates: int = 16  # sgd steps per train() iteration
    epsilon_start: float = 1.0
    epsilon_end: float = 0.05
    epsilon_decay_iters: int = 20
    learning_starts: int = 500  # min transitions before updates begin

    def training(self, *, lr: Optional[float] = None,
                 gamma: Optional[float] = None,
                 target_update_freq: Optional[int] = None,
                 train_batch_size: Optional[int] = None,
                 num_learner_updates: Optional[int] = None) -> "DQNConfig":
        kw = {k: v for k, v in dict(
            lr=lr, gamma=gamma,
            target_update_freq=target_update_freq).items() if v is not None}
        self.learner = replace(self.learner, **kw)
        if train_batch_size is not None:
            self.train_batch_size = train_batch_size
        if num_learner_updates is not None:
            self.num_learner_updates = num_learner_updates
        return self

    def build(self) -> "DQN":
        return DQN(copy.deepcopy(self))


class DQN(Algorithm):
    def __init__(self, config: DQNConfig):
        super().__init__(config)
        probe = make_vec_env(config.env, 1, seed=0)
        self.module_spec = RLModuleSpec(
            observation_dim=probe.observation_dim,
            action_dim=probe.action_dim,
            hidden=tuple(config.module_hidden))
        self.learner = DQNLearner(self.module_spec, config.learner,
                                  seed=config.seed)
        runner_cls = ray_tpu.remote(num_cpus=1)(DQNEnvRunner)
        self.runners = [
            runner_cls.remote(config.env, config.num_envs_per_env_runner,
                              self.module_spec, seed=config.seed + 1000 * i)
            for i in range(config.num_env_runners)]
        self.buffer = ReplayBufferGroup(
            num_shards=config.replay_shards,
            capacity=config.replay_capacity, alpha=config.replay_alpha)
        self._return_window: list[float] = []
        self._transitions = 0

    def _epsilon(self) -> float:
        cfg = self.config
        frac = min(1.0, self.iteration / max(1, cfg.epsilon_decay_iters))
        return cfg.epsilon_start + frac * (cfg.epsilon_end - cfg.epsilon_start)

    def train(self) -> dict:
        cfg = self.config
        eps = self._epsilon()
        weights = self.learner.get_weights()
        ray_tpu.get([r.set_weights.remote(weights) for r in self.runners],
                    timeout=120)
        batches = ray_tpu.get(
            [r.sample.remote(cfg.rollout_fragment_length, eps)
             for r in self.runners], timeout=300)
        add_refs = []
        for b in batches:
            self._return_window.extend(b.pop("episode_returns"))
            self._transitions += len(b["obs"])
            add_refs.append(self.buffer.add_batch(b))
        ray_tpu.get(add_refs, timeout=120)
        self._return_window = self._return_window[-100:]
        stats: dict = {}
        if self._transitions >= cfg.learning_starts:
            for _ in range(cfg.num_learner_updates):
                batch, index_map, w = self.buffer.sample(
                    cfg.train_batch_size, cfg.replay_beta)
                if not batch:
                    break
                stats, td = self.learner.update(batch, w)
                # TD errors feed back as new priorities (the prioritized
                # part of prioritized replay).
                self.buffer.update_priorities(index_map, td)
        self.iteration += 1
        return {
            "training_iteration": self.iteration,
            "num_env_steps_sampled": sum(len(b["obs"]) for b in batches),
            "num_transitions": self._transitions,
            "epsilon": eps,
            "episode_return_mean": (float(np.mean(self._return_window))
                                    if self._return_window else float("nan")),
            **{f"learner/{k}": v for k, v in stats.items()},
        }

    def stop(self):
        for r in self.runners:
            try:
                ray_tpu.kill(r)
            except Exception:
                pass
        self.buffer.stop()
