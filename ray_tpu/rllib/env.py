"""Vectorized environments (numpy, no gym dependency).

Parity target: reference rllib/env/ (EnvRunner-facing vector env API;
gymnasium's CartPole-v1 physics reproduced exactly — BASELINE.md names PPO
CartPole as a north-star workload). Vectorized in numpy so a whole batch of
envs steps in one call: host-side rollouts stay cheap while the learner
owns the accelerator.
"""

from __future__ import annotations

import numpy as np


class CartPoleVecEnv:
    """N independent CartPole-v1 instances (classic Barto-Sutton physics).

    obs: [N, 4] float32; actions: {0, 1}; reward 1.0 per live step;
    terminates at |x|>2.4, |theta|>12deg, or 500 steps (truncation)."""

    GRAVITY = 9.8
    MASSCART = 1.0
    MASSPOLE = 0.1
    LENGTH = 0.5  # half pole length
    FORCE_MAG = 10.0
    TAU = 0.02
    THETA_LIMIT = 12 * 2 * np.pi / 360
    X_LIMIT = 2.4
    MAX_STEPS = 500

    def __init__(self, num_envs: int, seed: int = 0):
        self.num_envs = num_envs
        self.rng = np.random.RandomState(seed)
        self.state = np.zeros((num_envs, 4), dtype=np.float64)
        self.steps = np.zeros(num_envs, dtype=np.int64)
        self.reset()

    @property
    def observation_dim(self) -> int:
        return 4

    @property
    def action_dim(self) -> int:
        return 2

    def reset(self) -> np.ndarray:
        self.state = self.rng.uniform(-0.05, 0.05, (self.num_envs, 4))
        self.steps[:] = 0
        return self.obs()

    def _reset_where(self, mask: np.ndarray):
        n = int(mask.sum())
        if n:
            self.state[mask] = self.rng.uniform(-0.05, 0.05, (n, 4))
            self.steps[mask] = 0

    def obs(self) -> np.ndarray:
        return self.state.astype(np.float32)

    def step(self, actions: np.ndarray):
        """Returns (obs, rewards, dones). Done envs auto-reset; the returned
        obs is the post-reset observation (standard vec-env contract)."""
        x, x_dot, th, th_dot = self.state.T
        force = np.where(actions == 1, self.FORCE_MAG, -self.FORCE_MAG)
        costh, sinth = np.cos(th), np.sin(th)
        total_mass = self.MASSCART + self.MASSPOLE
        polemass_length = self.MASSPOLE * self.LENGTH
        temp = (force + polemass_length * th_dot**2 * sinth) / total_mass
        th_acc = (self.GRAVITY * sinth - costh * temp) / (
            self.LENGTH * (4.0 / 3.0 - self.MASSPOLE * costh**2 / total_mass))
        x_acc = temp - polemass_length * th_acc * costh / total_mass
        x = x + self.TAU * x_dot
        x_dot = x_dot + self.TAU * x_acc
        th = th + self.TAU * th_dot
        th_dot = th_dot + self.TAU * th_acc
        self.state = np.stack([x, x_dot, th, th_dot], axis=1)
        self.steps += 1

        terminated = (np.abs(x) > self.X_LIMIT) | (np.abs(th) > self.THETA_LIMIT)
        truncated = self.steps >= self.MAX_STEPS
        dones = terminated | truncated
        rewards = np.ones(self.num_envs, dtype=np.float32)
        self._reset_where(dones)
        return self.obs(), rewards, dones.astype(np.float32)


ENV_REGISTRY = {
    "CartPole-v1": CartPoleVecEnv,
}


def make_vec_env(name: str, num_envs: int, seed: int = 0):
    if callable(name):
        return name(num_envs, seed)
    cls = ENV_REGISTRY.get(name)
    if cls is None:
        raise ValueError(f"unknown env {name!r}; register it in "
                         f"ray_tpu.rllib.env.ENV_REGISTRY")
    return cls(num_envs, seed=seed)
