"""Prioritized experience replay, sharded across buffer actors.

Parity target: reference rllib/utils/replay_buffers/prioritized_episode_
buffer.py (proportional prioritization, IS weights) hosted the way the
reference hosts buffers for distributed DQN — as actors the runners push
to and the learner samples from (sharding = one buffer actor per shard,
reference utils/actor_manager round-robin).
"""

from __future__ import annotations

import numpy as np

import ray_tpu


class PrioritizedReplayBuffer:
    """Proportional prioritized replay (Schaul et al. 2015): P(i) ~ p_i^a,
    importance weights w_i = (N * P(i))^-beta / max w. Circular numpy
    storage; O(n) sampling via cumulative sums (fine at 1e5 scale on the
    CPU hosts that run buffer actors)."""

    def __init__(self, capacity: int = 100_000, alpha: float = 0.6):
        self.capacity = capacity
        self.alpha = alpha
        self._storage: dict[str, np.ndarray] = {}
        self._priorities = np.zeros(capacity, np.float64)
        self._next = 0
        self._size = 0
        self._max_priority = 1.0

    def __len__(self):
        return self._size

    def add_batch(self, batch: dict):
        """batch: dict of [B, ...] arrays (obs/actions/rewards/next_obs/
        dones). New transitions get max priority so everything is seen at
        least once."""
        n = len(next(iter(batch.values())))
        if not self._storage:
            for k, v in batch.items():
                v = np.asarray(v)
                self._storage[k] = np.zeros((self.capacity,) + v.shape[1:],
                                            v.dtype)
        idx = (self._next + np.arange(n)) % self.capacity
        for k, v in batch.items():
            self._storage[k][idx] = np.asarray(v)
        self._priorities[idx] = self._max_priority
        self._next = int((self._next + n) % self.capacity)
        self._size = int(min(self._size + n, self.capacity))
        return self._size

    def sample(self, batch_size: int, beta: float = 0.4,
               normalize: bool = True):
        """-> (batch dict, indices, is_weights). Empty dict if not enough
        data yet. normalize=False returns RAW (N*P)^-beta weights so a
        sharded group can normalize by the GLOBAL max instead (per-shard
        maxima would systematically over-weight low-priority shards)."""
        if self._size == 0:
            return {}, np.zeros(0, np.int64), np.zeros(0, np.float32)
        pri = self._priorities[:self._size] ** self.alpha
        probs = pri / pri.sum()
        idx = np.random.choice(self._size, size=batch_size, p=probs)
        weights = ((self._size * probs[idx]) ** (-beta)).astype(np.float32)
        if normalize:
            weights = weights / weights.max()
        batch = {k: v[idx] for k, v in self._storage.items()}
        return batch, idx.astype(np.int64), weights

    def update_priorities(self, indices, priorities):
        priorities = np.abs(np.asarray(priorities, np.float64)) + 1e-6
        self._priorities[np.asarray(indices, np.int64)] = priorities
        self._max_priority = max(self._max_priority,
                                 float(priorities.max(initial=0.0)))

    def stats(self) -> dict:
        return {"size": self._size, "max_priority": self._max_priority}


class ReplayBufferGroup:
    """Sharded buffer fleet: runners push round-robin, the learner samples
    proportionally from every shard and merges (reference: multiple
    replay-shard actors behind the DQN algorithm)."""

    def __init__(self, num_shards: int = 1, capacity: int = 100_000,
                 alpha: float = 0.6):
        actor_cls = ray_tpu.remote(num_cpus=0)(PrioritizedReplayBuffer)
        per = max(1, capacity // num_shards)
        self.shards = [actor_cls.remote(per, alpha)
                       for _ in range(num_shards)]
        self._rr = 0

    def add_batch(self, batch: dict):
        shard = self.shards[self._rr % len(self.shards)]
        self._rr += 1
        return shard.add_batch.remote(batch)

    def sample(self, batch_size: int, beta: float):
        """-> (merged batch, [(shard_i, indices)], weights)."""
        per = max(1, batch_size // len(self.shards))
        reps = ray_tpu.get(
            [s.sample.remote(per, beta, False) for s in self.shards],
            timeout=120)
        batches, index_map, weights = [], [], []
        for i, (b, idx, w) in enumerate(reps):
            if len(idx) == 0:
                continue
            batches.append(b)
            index_map.append((i, idx))
            weights.append(w)
        if not batches:
            return {}, [], np.zeros(0, np.float32)
        merged = {k: np.concatenate([b[k] for b in batches])
                  for k in batches[0]}
        w = np.concatenate(weights)
        return merged, index_map, (w / w.max()).astype(np.float32)

    def update_priorities(self, index_map, td_errors: np.ndarray):
        off = 0
        refs = []
        for shard_i, idx in index_map:
            n = len(idx)
            refs.append(self.shards[shard_i].update_priorities.remote(
                idx, td_errors[off:off + n]))
            off += n
        ray_tpu.get(refs, timeout=60)

    def size(self) -> int:
        reps = ray_tpu.get([s.stats.remote() for s in self.shards],
                           timeout=60)
        return sum(r["size"] for r in reps)

    def stop(self):
        for s in self.shards:
            try:
                ray_tpu.kill(s)
            except Exception:
                pass
