"""IMPALA: async actor-learner with V-trace off-policy correction.

Parity target: reference rllib/algorithms/impala/impala.py:599 (async
sampling — the learner consumes whichever runner finishes first, never
barriering on the slowest — with V-trace importance-sampling correction
for the policy lag, per the IMPALA paper's rho/c-clipped targets).

TPU-native shape: the entire V-trace computation + loss + optimizer step
is ONE jit'd program (a backwards lax.scan over the rollout for the
v-trace recursion); the async harvest loop runs on the driver with
ray_tpu.wait over in-flight sample futures, re-syncing weights only to
the runner being relaunched (reference impala.py's per-runner weight
sync).
"""

from __future__ import annotations

import copy
from dataclasses import dataclass, field, replace
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
import optax

import ray_tpu
from ray_tpu.rllib.algorithm import Algorithm, AlgorithmConfig, EnvRunnerGroup


@dataclass(frozen=True)
class IMPALALearnerConfig:
    lr: float = 5e-4
    gamma: float = 0.99
    vf_coeff: float = 0.5
    entropy_coeff: float = 0.01
    max_grad_norm: float = 40.0
    rho_clip: float = 1.0  # V-trace rho-bar (value-target IS clip)
    c_clip: float = 1.0    # V-trace c-bar (trace-cutting IS clip)


@dataclass
class IMPALAConfig(AlgorithmConfig):
    learner: IMPALALearnerConfig = field(default_factory=IMPALALearnerConfig)
    #: batches consumed per train() call (one async harvest each)
    updates_per_iteration: int = 4

    def training(self, *, lr: Optional[float] = None,
                 gamma: Optional[float] = None,
                 entropy_coeff: Optional[float] = None,
                 vf_coeff: Optional[float] = None,
                 rho_clip: Optional[float] = None,
                 c_clip: Optional[float] = None,
                 updates_per_iteration: Optional[int] = None) -> "IMPALAConfig":
        kw = {k: v for k, v in dict(
            lr=lr, gamma=gamma, entropy_coeff=entropy_coeff,
            vf_coeff=vf_coeff, rho_clip=rho_clip, c_clip=c_clip).items()
            if v is not None}
        self.learner = replace(self.learner, **kw)
        if updates_per_iteration is not None:
            self.updates_per_iteration = updates_per_iteration
        return self

    def build(self) -> "IMPALA":
        return IMPALA(copy.deepcopy(self))


class IMPALALearner:
    """V-trace learner (reference impala_learner.py + vtrace_torch.py,
    recomputed here from the published recursion, jit'd end to end)."""

    def __init__(self, module: RLModule, config: IMPALALearnerConfig,
                 seed: int = 0):
        self.module = module
        self.cfg = config
        self.params = module.init(jax.random.PRNGKey(seed))
        # Adam rather than the reference's Atari-tuned RMSProp(eps=0.1):
        # that epsilon over-damps small-MLP control tasks by ~100x.
        self.opt = optax.chain(
            optax.clip_by_global_norm(config.max_grad_norm),
            optax.adam(config.lr))
        self.opt_state = self.opt.init(self.params)
        self._update = jax.jit(self._update_impl)

    def _vtrace(self, values, last_value, rewards, dones, rhos):
        """vs_t = V_t + delta_t + gamma c_t (vs_{t+1} - V_{t+1}); backwards
        scan over T. Returns (vs [T,N], pg_advantages [T,N])."""
        cfg = self.cfg
        rho = jnp.minimum(cfg.rho_clip, rhos)
        c = jnp.minimum(cfg.c_clip, rhos)
        nonterm = 1.0 - dones
        next_values = jnp.concatenate([values[1:], last_value[None]], axis=0)
        deltas = rho * (rewards + cfg.gamma * next_values * nonterm - values)

        def back(carry, xs):
            acc = carry  # vs_{t+1} - V_{t+1}
            delta_t, c_t, nt_t = xs
            acc = delta_t + cfg.gamma * c_t * nt_t * acc
            return acc, acc

        _, acc = jax.lax.scan(back, jnp.zeros_like(values[0]),
                              (deltas, c, nonterm), reverse=True)
        vs = values + acc
        next_vs = jnp.concatenate([vs[1:], last_value[None]], axis=0)
        pg_adv = rho * (rewards + cfg.gamma * next_vs * nonterm - values)
        return jax.lax.stop_gradient(vs), jax.lax.stop_gradient(pg_adv)

    def _loss(self, params, batch):
        cfg = self.cfg
        T, N = batch["obs"].shape[:2]
        flat_obs = batch["obs"].reshape(T * N, -1)
        logits, values = self.module.forward_train(params, flat_obs)
        logits = logits.reshape(T, N, -1)
        values = values.reshape(T, N)
        logp_all = jax.nn.log_softmax(logits)
        logp = jnp.take_along_axis(
            logp_all, batch["actions"][..., None], axis=-1)[..., 0]
        rhos = jnp.exp(logp - batch["logp_old"])
        _, last_value = self.module.forward_train(params, batch["last_obs"])
        vs, pg_adv = self._vtrace(values, last_value, batch["rewards"],
                                  batch["dones"], rhos)
        pi_loss = -(logp * pg_adv).mean()
        vf_loss = jnp.mean((values - vs) ** 2)
        entropy = -jnp.sum(jnp.exp(logp_all) * logp_all, axis=-1).mean()
        loss = pi_loss + cfg.vf_coeff * vf_loss - cfg.entropy_coeff * entropy
        return loss, {"pi_loss": pi_loss, "vf_loss": vf_loss,
                      "entropy": entropy}

    def _update_impl(self, params, opt_state, batch):
        (loss, aux), grads = jax.value_and_grad(
            self._loss, has_aux=True)(params, batch)
        updates, opt_state = self.opt.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        aux["loss"] = loss
        return params, opt_state, aux

    def update(self, batch: dict) -> dict:
        jb = {
            "obs": jnp.asarray(batch["obs"]),
            "actions": jnp.asarray(batch["actions"].astype(np.int32)),
            "logp_old": jnp.asarray(batch["logp_old"]),
            "rewards": jnp.asarray(batch["rewards"]),
            "dones": jnp.asarray(batch["dones"]),
            "last_obs": jnp.asarray(batch["last_obs"]),
        }
        self.params, self.opt_state, stats = self._update(
            self.params, self.opt_state, jb)
        return {k: float(v) for k, v in stats.items()}

    def get_weights(self):
        return jax.tree_util.tree_map(np.asarray, self.params)


class IMPALA(Algorithm):
    """Async harvest loop: every runner always has a sample() in flight;
    train() consumes the first `updates_per_iteration` arrivals, updating
    the learner on each and relaunching THAT runner with fresh weights."""

    def __init__(self, config: IMPALAConfig):
        super().__init__(config)
        self._bootstrap(lambda module: IMPALALearner(
            module, config.learner, seed=config.seed))
        self._inflight: dict = {}  # ref -> runner
        w = self.learner.get_weights()
        for r in self.runners.runners:
            ray_tpu.get(r.set_weights.remote(w), timeout=120)
            self._inflight[r.sample.remote(config.rollout_fragment_length)] = r

    def train(self) -> dict:
        cfg = self.config
        steps = 0
        stats: dict = {}
        for _ in range(cfg.updates_per_iteration):
            ready, _ = ray_tpu.wait(list(self._inflight), num_returns=1,
                                    timeout=300)
            if not ready:
                raise RuntimeError(
                    "IMPALA: no env-runner produced a sample within 300s "
                    f"({len(self._inflight)} in flight) — runner dead or "
                    "sampling stalled")
            ref = ready[0]
            runner = self._inflight.pop(ref)
            batch = ray_tpu.get(ref, timeout=60)
            stats = self.learner.update(batch)
            self._return_window.extend(batch["episode_returns"])
            steps += batch["obs"].shape[0] * batch["obs"].shape[1]
            # Relaunch ONLY this runner, with post-update weights (the
            # policy lag this creates is exactly what V-trace corrects).
            runner.set_weights.remote(self.learner.get_weights())
            self._inflight[runner.sample.remote(
                cfg.rollout_fragment_length)] = runner
        self._return_window = self._return_window[-100:]
        self.iteration += 1
        return {
            "training_iteration": self.iteration,
            "num_env_steps_sampled": steps,
            "episode_return_mean": (float(np.mean(self._return_window))
                                    if self._return_window else float("nan")),
            **{f"learner/{k}": v for k, v in stats.items()},
        }

