"""Algorithm + PPO: the training driver.

Parity target: reference rllib/algorithms/algorithm.py:208 (Algorithm —
config.build() -> .train() iterations) + algorithms/ppo/ppo.py. The
structure mirrors the reference new API stack: EnvRunnerGroup actors
sample in parallel, the local Learner (jit'd, accelerator-resident)
updates, weights broadcast back. Also a Tune trainable: Algorithm exposes
step-wise train() so tune schedulers can early-stop it.
"""

from __future__ import annotations

import copy
from dataclasses import dataclass, field, replace
from typing import Any, Optional

import numpy as np

import ray_tpu
from ray_tpu.rllib.env import make_vec_env
from ray_tpu.rllib.env_runner import SingleAgentEnvRunner
from ray_tpu.rllib.learner import PPOLearner, PPOLearnerConfig, compute_gae
from ray_tpu.rllib.rl_module import RLModule, RLModuleSpec


@dataclass
class AlgorithmConfig:
    """reference algorithm_config.py builder (environment()/env_runners()/
    training() chainers)."""

    env: Any = "CartPole-v1"
    num_env_runners: int = 2
    num_envs_per_env_runner: int = 8
    rollout_fragment_length: int = 64
    seed: int = 0
    module_hidden: tuple = (64, 64)

    def environment(self, env) -> "AlgorithmConfig":
        self.env = env
        return self

    def env_runners(self, *, num_env_runners: Optional[int] = None,
                    num_envs_per_env_runner: Optional[int] = None,
                    rollout_fragment_length: Optional[int] = None) -> "AlgorithmConfig":
        if num_env_runners is not None:
            self.num_env_runners = num_env_runners
        if num_envs_per_env_runner is not None:
            self.num_envs_per_env_runner = num_envs_per_env_runner
        if rollout_fragment_length is not None:
            self.rollout_fragment_length = rollout_fragment_length
        return self

    def build(self) -> "Algorithm":
        raise NotImplementedError


@dataclass
class PPOConfig(AlgorithmConfig):
    learner: PPOLearnerConfig = field(default_factory=PPOLearnerConfig)

    def training(self, *, lr: Optional[float] = None,
                 gamma: Optional[float] = None,
                 clip: Optional[float] = None,
                 entropy_coeff: Optional[float] = None,
                 num_epochs: Optional[int] = None,
                 minibatch_size: Optional[int] = None) -> "PPOConfig":
        kw = {k: v for k, v in dict(
            lr=lr, gamma=gamma, clip=clip, entropy_coeff=entropy_coeff,
            num_epochs=num_epochs, minibatch_size=minibatch_size).items()
            if v is not None}
        self.learner = replace(self.learner, **kw)
        return self

    def build(self) -> "PPO":
        return PPO(copy.deepcopy(self))


class EnvRunnerGroup:
    """reference env_runner_group.py:71 — the actor fleet."""

    def __init__(self, config: AlgorithmConfig, module_spec: RLModuleSpec):
        runner_cls = ray_tpu.remote(num_cpus=1)(SingleAgentEnvRunner)
        self.runners = [
            runner_cls.remote(config.env, config.num_envs_per_env_runner,
                              module_spec, seed=config.seed + 1000 * i)
            for i in range(config.num_env_runners)
        ]

    def sync_weights(self, weights):
        ray_tpu.get([r.set_weights.remote(weights) for r in self.runners],
                    timeout=120)

    def sample(self, num_steps: int) -> list[dict]:
        return ray_tpu.get(
            [r.sample.remote(num_steps) for r in self.runners], timeout=300)

    def stop(self):
        for r in self.runners:
            try:
                ray_tpu.kill(r)
            except Exception:
                pass


class Algorithm:
    def __init__(self, config: AlgorithmConfig):
        self.config = config
        self.iteration = 0

    def _bootstrap(self, make_learner):
        """Shared setup for concrete algorithms: probe the env for the
        module spec, build module + learner (via make_learner(module)) and
        the env-runner fleet."""
        config = self.config
        probe = make_vec_env(config.env, 1, seed=0)
        self.module_spec = RLModuleSpec(
            observation_dim=probe.observation_dim,
            action_dim=probe.action_dim,
            hidden=tuple(config.module_hidden))
        self.module = RLModule(self.module_spec)
        self.learner = make_learner(self.module)
        self.runners = EnvRunnerGroup(config, self.module_spec)
        self._return_window: list[float] = []

    def train(self) -> dict:
        raise NotImplementedError

    def stop(self):
        try:
            self.runners.stop()
        except AttributeError:
            pass


class PPO(Algorithm):
    def __init__(self, config: PPOConfig):
        super().__init__(config)
        self._bootstrap(lambda module: PPOLearner(
            module, config.learner, seed=config.seed))

    def train(self) -> dict:
        """One iteration: parallel sample -> GAE -> minibatched PPO epochs
        -> weight broadcast. Returns reference-shaped metrics."""
        cfg = self.config
        self.runners.sync_weights(self.learner.get_weights())
        batches = self.runners.sample(cfg.rollout_fragment_length)

        # Stack runner batches along the env axis: [T, N_total, ...]
        cat = {k: np.concatenate([b[k] for b in batches], axis=1)
               for k in ("obs", "actions", "logp_old", "values", "rewards",
                         "dones")}
        last_values = np.concatenate([b["last_values"] for b in batches])
        lc = self.learner.cfg
        adv, targets = compute_gae(cat["rewards"], cat["values"],
                                   cat["dones"], last_values,
                                   lc.gamma, lc.gae_lambda)
        adv = (adv - adv.mean()) / (adv.std() + 1e-8)
        T, N = cat["obs"].shape[:2]
        flat = {
            "obs": cat["obs"].reshape(T * N, -1),
            "actions": cat["actions"].reshape(T * N).astype(np.int32),
            "logp_old": cat["logp_old"].reshape(T * N),
            "advantages": adv.reshape(T * N).astype(np.float32),
            "value_targets": targets.reshape(T * N).astype(np.float32),
        }
        stats = self.learner.update(flat)

        for b in batches:
            self._return_window.extend(b["episode_returns"])
        self._return_window = self._return_window[-100:]
        self.iteration += 1
        return {
            "training_iteration": self.iteration,
            "num_env_steps_sampled": T * N,
            "episode_return_mean": (float(np.mean(self._return_window))
                                    if self._return_window else float("nan")),
            **{f"learner/{k}": v for k, v in stats.items()},
        }

