"""PPO Learner: the jit'd update step.

Parity target: reference rllib/core/learner/learner.py:107 +
algorithms/ppo/ppo_learner.py (clipped surrogate + value loss + entropy
bonus, minibatched epochs). TPU-native: the ENTIRE update — all epochs and
minibatches — is one compiled program (lax.scan over minibatch indices),
so the accelerator never round-trips to Python mid-update; on a mesh the
same step runs under pjit with batch sharded over dp and grads psum'd by
XLA.
"""

from __future__ import annotations

import functools
from dataclasses import dataclass

import jax
import jax.numpy as jnp
import numpy as np
import optax

from ray_tpu.rllib.rl_module import RLModule


@dataclass(frozen=True)
class PPOLearnerConfig:
    lr: float = 3e-4
    gamma: float = 0.99
    gae_lambda: float = 0.95
    clip: float = 0.2
    vf_coeff: float = 0.5
    entropy_coeff: float = 0.01
    num_epochs: int = 4
    minibatch_size: int = 128
    max_grad_norm: float = 0.5


class PPOLearner:
    def __init__(self, module: RLModule, config: PPOLearnerConfig,
                 seed: int = 0):
        self.module = module
        self.cfg = config
        self.params = module.init(jax.random.PRNGKey(seed))
        self.opt = optax.chain(
            optax.clip_by_global_norm(config.max_grad_norm),
            optax.adam(config.lr))
        self.opt_state = self.opt.init(self.params)
        self._update = jax.jit(self._update_impl)
        self._rng = jax.random.PRNGKey(seed + 1)

    # ------------------------------------------------------------- update
    def _loss(self, params, batch):
        cfg = self.cfg
        logits, values = self.module.forward_train(params, batch["obs"])
        logp_all = jax.nn.log_softmax(logits)
        logp = jnp.take_along_axis(
            logp_all, batch["actions"][:, None], axis=-1)[:, 0]
        ratio = jnp.exp(logp - batch["logp_old"])
        adv = batch["advantages"]
        surr = jnp.minimum(
            ratio * adv,
            jnp.clip(ratio, 1 - cfg.clip, 1 + cfg.clip) * adv)
        pi_loss = -surr.mean()
        vf_loss = jnp.mean((values - batch["value_targets"]) ** 2)
        entropy = -jnp.sum(jnp.exp(logp_all) * logp_all, axis=-1).mean()
        loss = pi_loss + cfg.vf_coeff * vf_loss - cfg.entropy_coeff * entropy
        return loss, {"pi_loss": pi_loss, "vf_loss": vf_loss,
                      "entropy": entropy}

    def _update_impl(self, params, opt_state, batch, rng):
        cfg = self.cfg
        n = batch["obs"].shape[0]
        # A batch smaller than minibatch_size trains as one (smaller)
        # minibatch instead of crashing the reshape.
        mb_size = min(cfg.minibatch_size, n)
        n_mb = max(1, n // mb_size)
        usable = n_mb * mb_size

        def epoch(carry, erng):
            params, opt_state = carry
            perm = jax.random.permutation(erng, n)[:usable]
            mbs = perm.reshape(n_mb, mb_size)

            def mb_step(carry, idx):
                params, opt_state = carry
                mb = {k: v[idx] for k, v in batch.items()}
                (loss, aux), grads = jax.value_and_grad(
                    self._loss, has_aux=True)(params, mb)
                updates, opt_state = self.opt.update(grads, opt_state, params)
                params = optax.apply_updates(params, updates)
                return (params, opt_state), (loss, aux)

            (params, opt_state), (losses, auxs) = jax.lax.scan(
                mb_step, (params, opt_state), mbs)
            return (params, opt_state), (losses.mean(),
                                         {k: v.mean() for k, v in auxs.items()})

        erngs = jax.random.split(rng, cfg.num_epochs)
        (params, opt_state), (losses, auxs) = jax.lax.scan(
            epoch, (params, opt_state), erngs)
        stats = {k: v.mean() for k, v in auxs.items()}
        stats["loss"] = losses.mean()
        return params, opt_state, stats

    def update(self, batch: dict) -> dict:
        """batch: numpy dict with obs/actions/logp_old/advantages/
        value_targets. Returns training stats."""
        jb = {k: jnp.asarray(v) for k, v in batch.items()}
        self._rng, sub = jax.random.split(self._rng)
        self.params, self.opt_state, stats = self._update(
            self.params, self.opt_state, jb, sub)
        return {k: float(v) for k, v in stats.items()}

    def get_weights(self):
        return jax.tree_util.tree_map(np.asarray, self.params)


def compute_gae(rewards, values, dones, last_values, gamma, lam):
    """GAE over [T, N] rollouts (reference postprocessing
    compute_advantages). Pure numpy: runs where the rollout lives."""
    T = rewards.shape[0]
    adv = np.zeros_like(rewards)
    last_gae = np.zeros_like(rewards[0])
    next_values = last_values
    for t in reversed(range(T)):
        nonterminal = 1.0 - dones[t]
        delta = rewards[t] + gamma * next_values * nonterminal - values[t]
        last_gae = delta + gamma * lam * nonterminal * last_gae
        adv[t] = last_gae
        next_values = values[t]
    value_targets = adv + values
    return adv, value_targets
