"""RLModule: the policy/value network abstraction.

Parity target: reference rllib/core/rl_module/rl_module.py:260 (the new-API
RLModule with forward_inference / forward_exploration / forward_train) —
implemented as a flax module whose forward passes are pure functions, so
the learner jits the whole PPO update and the env-runner jits action
sampling; on TPU the same module drops into a pjit mesh unchanged.
"""

from __future__ import annotations

from dataclasses import dataclass, field

import flax.linen as nn
import jax
import jax.numpy as jnp


@dataclass(frozen=True)
class RLModuleSpec:
    """reference rl_module.RLModuleSpec: how to build the module."""

    observation_dim: int
    action_dim: int
    hidden: tuple = (64, 64)


class PolicyValueNet(nn.Module):
    spec: RLModuleSpec

    @nn.compact
    def __call__(self, obs):
        x = obs
        for i, h in enumerate(self.spec.hidden):
            x = nn.tanh(nn.Dense(h, name=f"fc{i}")(x))
        logits = nn.Dense(self.spec.action_dim, name="pi")(x)
        value = nn.Dense(1, name="vf")(x)[..., 0]
        return logits, value


class RLModule:
    """Bundles the flax net with the reference's forward_* surface."""

    def __init__(self, spec: RLModuleSpec):
        self.spec = spec
        self.net = PolicyValueNet(spec)

    def init(self, rng):
        dummy = jnp.zeros((1, self.spec.observation_dim), jnp.float32)
        return self.net.init(rng, dummy)

    def forward_train(self, params, obs):
        """-> (logits, values); used inside the PPO loss."""
        return self.net.apply(params, obs)

    def forward_exploration(self, params, obs, rng):
        """Sample actions + logp + value (env-runner rollout step)."""
        logits, value = self.net.apply(params, obs)
        action = jax.random.categorical(rng, logits, axis=-1)
        logp = jax.nn.log_softmax(logits)[jnp.arange(action.shape[0]), action]
        return action, logp, value

    def forward_inference(self, params, obs):
        """Greedy actions (serving/eval)."""
        logits, _ = self.net.apply(params, obs)
        return jnp.argmax(logits, axis=-1)
