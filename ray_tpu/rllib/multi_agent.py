"""Multi-agent: env runner with per-policy module mapping + MA-PPO.

Parity target: reference rllib/env/multi_agent_env_runner.py (one runner
steps an env hosting MANY agents; a policy_mapping_fn routes each agent id
to a module id; sample() returns per-MODULE batches) +
examples/multi_agent's MultiAgentCartPole, and the MultiAgentRLModule /
per-module Learner update of the new API stack.
"""

from __future__ import annotations

import copy
from dataclasses import dataclass, field
from typing import Any, Callable, Optional

import numpy as np

import jax
import jax.numpy as jnp

import ray_tpu
from ray_tpu.rllib.algorithm import Algorithm, AlgorithmConfig
from ray_tpu.rllib.env import CartPoleVecEnv
from ray_tpu.rllib.learner import PPOLearner, PPOLearnerConfig, compute_gae
from ray_tpu.rllib.rl_module import RLModule, RLModuleSpec


class MultiAgentCartPole:
    """N vectorized copies of an M-agent CartPole: every agent balances its
    own pole each step (reference examples MultiAgentCartPole — independent
    dynamics, shared episode clock). obs()/step() speak dicts keyed by
    agent id, [N, ...] per agent."""

    def __init__(self, num_envs: int, num_agents: int = 2, seed: int = 0):
        self.num_envs = num_envs
        self.agent_ids = [f"agent_{i}" for i in range(num_agents)]
        self._envs = {aid: CartPoleVecEnv(num_envs, seed=seed + 97 * i)
                      for i, aid in enumerate(self.agent_ids)}

    @property
    def observation_dim(self) -> int:
        return 4

    @property
    def action_dim(self) -> int:
        return 2

    def obs(self) -> dict:
        return {aid: env.obs() for aid, env in self._envs.items()}

    def step(self, actions: dict):
        """actions: {agent_id: [N]} -> (obs, rewards, dones) dicts."""
        out_o, out_r, out_d = {}, {}, {}
        for aid, env in self._envs.items():
            o, r, d = env.step(actions[aid])
            out_o[aid], out_r[aid], out_d[aid] = o, r, d
        return out_o, out_r, out_d


class MultiAgentEnvRunner:
    """Rollout actor for multi-agent envs: holds one RLModule per POLICY
    (module id), maps agents to policies via policy_mapping_fn, and
    returns per-policy [T, N, ...] batches (reference
    multi_agent_env_runner.py sample())."""

    def __init__(self, env_ctor, num_envs: int, spec: RLModuleSpec,
                 module_ids: list, policy_mapping: dict, seed: int = 0):
        self.env = env_ctor(num_envs, seed=seed)
        self.module_ids = list(module_ids)
        self.policy_mapping = dict(policy_mapping)  # agent_id -> module_id
        self.modules = {mid: RLModule(spec) for mid in self.module_ids}
        self.params: dict = {}
        self._rng = jax.random.PRNGKey(seed)
        self._explore = {mid: jax.jit(m.forward_exploration)
                        for mid, m in self.modules.items()}
        self.obs = self.env.obs()
        self._ep_ret = {aid: np.zeros(num_envs) for aid in self.env.agent_ids}
        self._done_returns: dict[str, list] = {aid: [] for aid in self.env.agent_ids}

    def set_weights(self, weights: dict):
        self.params = weights
        return True

    def sample(self, num_steps: int) -> dict:
        """-> {module_id: batch} with per-module trajectories + metrics."""
        assert self.params, "set_weights first"
        T, N = num_steps, self.env.num_envs
        agents = self.env.agent_ids
        buf = {aid: {"obs": np.zeros((T, N, self.env.observation_dim), np.float32),
                     "actions": np.zeros((T, N), np.int32),
                     "logp_old": np.zeros((T, N), np.float32),
                     "values": np.zeros((T, N), np.float32),
                     "rewards": np.zeros((T, N), np.float32),
                     "dones": np.zeros((T, N), np.float32)}
               for aid in agents}
        for t in range(T):
            actions = {}
            for aid in agents:
                mid = self.policy_mapping[aid]
                self._rng, sub = jax.random.split(self._rng)
                a, logp, v = self._explore[mid](
                    self.params[mid], jnp.asarray(self.obs[aid]), sub)
                buf[aid]["obs"][t] = self.obs[aid]
                buf[aid]["actions"][t] = np.asarray(a)
                buf[aid]["logp_old"][t] = np.asarray(logp)
                buf[aid]["values"][t] = np.asarray(v)
                actions[aid] = np.asarray(a)
            self.obs, rewards, dones = self.env.step(actions)
            for aid in agents:
                buf[aid]["rewards"][t] = rewards[aid]
                buf[aid]["dones"][t] = dones[aid]
                self._ep_ret[aid] += rewards[aid]
                fin = dones[aid].astype(bool)
                if fin.any():
                    self._done_returns[aid].extend(
                        self._ep_ret[aid][fin].tolist())
                    self._ep_ret[aid][fin] = 0.0
        # Group agent trajectories by MODULE (multiple agents can share a
        # policy: their batches concatenate along the env axis).
        out: dict[str, dict] = {}
        for aid in agents:
            mid = self.policy_mapping[aid]
            _, last_v = self.modules[mid].forward_train(
                self.params[mid], jnp.asarray(self.obs[aid]))
            b = dict(buf[aid])
            b["last_values"] = np.asarray(last_v)
            b["episode_returns"] = self._done_returns[aid]
            if mid not in out:
                out[mid] = b
            else:
                prev = out[mid]
                for k in ("obs", "actions", "logp_old", "values", "rewards",
                          "dones"):
                    prev[k] = np.concatenate([prev[k], b[k]], axis=1)
                prev["last_values"] = np.concatenate(
                    [prev["last_values"], b["last_values"]])
                prev["episode_returns"] = (prev["episode_returns"]
                                           + b["episode_returns"])
        self._done_returns = {aid: [] for aid in agents}
        return out


@dataclass
class MultiAgentPPOConfig(AlgorithmConfig):
    num_agents: int = 2
    learner: PPOLearnerConfig = field(default_factory=PPOLearnerConfig)
    #: agent_id -> module_id; default: every agent gets its OWN policy
    policy_mapping: Optional[dict] = None

    def multi_agent(self, *, num_agents: Optional[int] = None,
                    policy_mapping: Optional[dict] = None
                    ) -> "MultiAgentPPOConfig":
        if num_agents is not None:
            self.num_agents = num_agents
        if policy_mapping is not None:
            self.policy_mapping = policy_mapping
        return self

    def build(self) -> "MultiAgentPPO":
        return MultiAgentPPO(copy.deepcopy(self))


class MultiAgentPPO(Algorithm):
    """Independent PPO per policy module over a multi-agent env (the
    reference's default multi-agent training: one Learner update per
    module from its own agents' batches)."""

    def __init__(self, config: MultiAgentPPOConfig):
        super().__init__(config)
        agent_ids = [f"agent_{i}" for i in range(config.num_agents)]
        self.policy_mapping = config.policy_mapping or {
            aid: f"policy_{i}" for i, aid in enumerate(agent_ids)}
        self.module_ids = sorted(set(self.policy_mapping.values()))
        env_ctor = (config.env if callable(config.env) else
                    (lambda n, seed=0, _na=config.num_agents:
                     MultiAgentCartPole(n, _na, seed)))
        probe = env_ctor(1, seed=0)
        self.module_spec = RLModuleSpec(
            observation_dim=probe.observation_dim,
            action_dim=probe.action_dim,
            hidden=tuple(config.module_hidden))
        self.learners = {
            mid: PPOLearner(RLModule(self.module_spec), config.learner,
                            seed=config.seed + 31 * i)
            for i, mid in enumerate(self.module_ids)}
        runner_cls = ray_tpu.remote(num_cpus=1)(MultiAgentEnvRunner)
        self.runners = [
            runner_cls.remote(env_ctor, config.num_envs_per_env_runner,
                              self.module_spec, self.module_ids,
                              self.policy_mapping,
                              seed=config.seed + 1000 * i)
            for i in range(config.num_env_runners)]
        self._return_window: list[float] = []

    def train(self) -> dict:
        cfg = self.config
        weights = {mid: l.get_weights() for mid, l in self.learners.items()}
        ray_tpu.get([r.set_weights.remote(weights) for r in self.runners],
                    timeout=120)
        per_runner = ray_tpu.get(
            [r.sample.remote(cfg.rollout_fragment_length)
             for r in self.runners], timeout=300)
        steps = 0
        stats: dict = {}
        for mid in self.module_ids:
            batches = [pr[mid] for pr in per_runner if mid in pr]
            if not batches:
                continue
            cat = {k: np.concatenate([b[k] for b in batches], axis=1)
                   for k in ("obs", "actions", "logp_old", "values",
                             "rewards", "dones")}
            last_values = np.concatenate([b["last_values"] for b in batches])
            lc = self.learners[mid].cfg
            adv, targets = compute_gae(cat["rewards"], cat["values"],
                                       cat["dones"], last_values,
                                       lc.gamma, lc.gae_lambda)
            adv = (adv - adv.mean()) / (adv.std() + 1e-8)
            T, N = cat["obs"].shape[:2]
            flat = {
                "obs": cat["obs"].reshape(T * N, -1),
                "actions": cat["actions"].reshape(T * N).astype(np.int32),
                "logp_old": cat["logp_old"].reshape(T * N),
                "advantages": adv.reshape(T * N).astype(np.float32),
                "value_targets": targets.reshape(T * N).astype(np.float32),
            }
            st = self.learners[mid].update(flat)
            stats[mid] = st
            steps += T * N
            for b in batches:
                self._return_window.extend(b["episode_returns"])
        self._return_window = self._return_window[-200:]
        self.iteration += 1
        return {
            "training_iteration": self.iteration,
            "num_env_steps_sampled": steps,
            "episode_return_mean": (float(np.mean(self._return_window))
                                    if self._return_window else float("nan")),
            **{f"learner/{mid}/loss": s.get("loss", float("nan"))
               for mid, s in stats.items()},
        }

    def stop(self):
        for r in self.runners:
            try:
                ray_tpu.kill(r)
            except Exception:
                pass
