"""Token-batch stream ring: the decode hot loop's reply transport.

Grown from the compiled-graph shm channel (experimental/channel.py — the
~22us futex-ring round-trip primitive) into a **multi-record bounded byte
ring** for token streams: where the SPSC Channel carries exactly one
in-flight message (seq/ack, capacity-1 backpressure), StreamRing lays
variable-length records head-to-tail in a circular byte region so

- the producer appends without waiting for the consumer to ack each
  record (it parks only when the ring is FULL — bounded buffering, never
  unbounded), and
- the consumer drains EVERY complete record in one wakeup (`read_batch`),
  so a token stream costs one reader wakeup per burst, not one per token.

This is the serve→engine reply path of README "Serving hot loop": the
replica's token pump writes SSE chunk records, the HTTP proxy reads
batches and coalesces them into single socket flushes — zero per-token
RPC, zero per-token ObjectRef. The same record contract is generalized
onto the rpc transport for cross-host streams by dag/push_stream.py
(PushStreamWriter/Reader: identical write/read_batch/close semantics,
credit-window backpressure instead of ring-full parking); the serve
handshake picks shm ring when it can attach, push-stream otherwise. Writers may be multiple threads of ONE
process (engine emit thread + pump + error paths): writes serialize on an
in-process lock. Cross-process stays single-producer/single-consumer,
like the Channel it grows from.

Layout (header 64B, must stay self-consistent — nothing else maps it):

    [wpos u64][rpos u64][closed u32][pad ...]  then `capacity` data bytes

wpos/rpos are MONOTONIC byte offsets (position in ring = offset %
capacity); a record is [len u32][payload], never wrapping: when the tail
can't fit the header+payload contiguously, a pad marker (len=0xFFFFFFFF)
skips to the next wrap. Publish order matters: payload bytes first, then
the wpos store — same discipline as the Channel's size-then-seq.
"""

from __future__ import annotations

import mmap
import os
import pickle
import struct
import threading
import time

_HDR = struct.Struct("<QQI")
_DATA = 64
_LEN = struct.Struct("<I")
_PAD = 0xFFFFFFFF

#: Poll interval while parked (write-full / read-empty). The futex-backed
#: Channel sleeps in the kernel; this ring poll-sleeps the same way the
#: Channel's pure-Python fallback does — a parked end costs ~60us of wake
#: latency, orders below the per-token RPC round trip it replaces.
_POLL_S = 0.000005


class RingClosed(Exception):
    """The writer closed the ring and every record has been drained."""


class StreamRing:
    """Named bounded stream ring over /dev/shm. Both ends open by name;
    the handle pickles as (name, capacity) so it can ride request
    metadata to the producing process."""

    def __init__(self, name: str, capacity: int = 1 << 20,
                 _create: bool = True):
        if capacity < 4096:
            raise ValueError(f"ring capacity {capacity} < 4096B")
        self.name = name
        self.capacity = capacity
        self._path = os.path.join("/dev/shm", f"rtring_{name}")
        total = _DATA + capacity
        exists = os.path.exists(self._path)
        if not _create and not exists:
            raise FileNotFoundError(f"stream ring {name!r} does not exist")
        fd = os.open(self._path, os.O_CREAT | os.O_RDWR, 0o600)
        try:
            if not exists:
                os.ftruncate(fd, total)
            self._mm = mmap.mmap(fd, total)
        finally:
            os.close(fd)
        self._wlock = threading.Lock()  # multi-thread producers, one process

    # ------------------------------------------------------------- header
    def _load(self) -> tuple[int, int, int]:
        return _HDR.unpack_from(self._mm, 0)

    def _store_wpos(self, wpos: int) -> None:
        struct.pack_into("<Q", self._mm, 0, wpos)

    def _store_rpos(self, rpos: int) -> None:
        struct.pack_into("<Q", self._mm, 8, rpos)

    # -------------------------------------------------------------- write
    def write(self, value, timeout: float | None = None) -> None:
        """Append one record; parks while the ring lacks space (consumer
        backpressure — the producer NEVER buffers unboundedly). Raises
        TimeoutError on a stalled consumer, ValueError on a record too
        large to ever fit, RingClosed after close_write()."""
        blob = pickle.dumps(value, protocol=5)
        need = _LEN.size + len(blob)
        # A record must fit contiguously even in the worst wrap position.
        if need > self.capacity // 2:
            raise ValueError(
                f"record {len(blob)}B exceeds ring record cap "
                f"({self.capacity // 2 - _LEN.size}B for a "
                f"{self.capacity}B ring)")
        deadline = None if timeout is None else time.monotonic() + timeout
        with self._wlock:
            wpos, rpos, closed = self._load()
            if closed:
                raise RingClosed("stream ring is closed for writing")
            off = wpos % self.capacity
            tail = self.capacity - off
            pad = tail if tail < need else 0  # record would wrap: skip tail
            while (wpos + pad + need) - rpos > self.capacity:
                if deadline is not None and time.monotonic() > deadline:
                    raise TimeoutError(
                        "stream ring write timed out (consumer stalled)")
                time.sleep(_POLL_S)
                rpos = self._load()[1]
            if pad:
                if tail >= _LEN.size:
                    _LEN.pack_into(self._mm, _DATA + off, _PAD)
                # tail < 4B: too small for even a marker; the reader skips
                # sub-header tails unconditionally.
                wpos += pad
                off = 0
            start = _DATA + off
            self._mm[start + _LEN.size:start + need] = blob
            _LEN.pack_into(self._mm, start, len(blob))
            self._store_wpos(wpos + need)

    def close_write(self) -> None:
        """End-of-stream: readers drain what remains, then read_batch
        raises RingClosed. Idempotent."""
        with self._wlock:
            struct.pack_into("<I", self._mm, 16, 1)

    # --------------------------------------------------------------- read
    def read_batch(self, timeout: float | None = None,
                   max_bytes: int | None = None) -> list:
        """Block until at least one record is available, then return EVERY
        complete record currently in the ring (one consumer wakeup drains
        the burst). Raises TimeoutError when nothing arrives in time and
        RingClosed once the writer closed and the ring is drained."""
        deadline = None if timeout is None else time.monotonic() + timeout
        while True:
            wpos, rpos, closed = self._load()
            if wpos > rpos:
                break
            if closed:
                raise RingClosed("stream ring closed and drained")
            if deadline is not None and time.monotonic() > deadline:
                raise TimeoutError("stream ring read timed out")
            time.sleep(_POLL_S)
        out: list = []
        budget = self.capacity if max_bytes is None else max_bytes
        pos = rpos
        while pos < wpos and budget > 0:
            off = pos % self.capacity
            tail = self.capacity - off
            if tail < _LEN.size:
                pos += tail  # sub-header tail: always padding
                continue
            n = _LEN.unpack_from(self._mm, _DATA + off)[0]
            if n == _PAD:
                pos += tail
                continue
            start = _DATA + off + _LEN.size
            out.append(pickle.loads(self._mm[start:start + n]))
            pos += _LEN.size + n
            budget -= _LEN.size + n
        # ONE rpos publish per batch: the producer sees the whole burst's
        # space freed at once (fewer parked-writer wakeups).
        self._store_rpos(pos)
        return out

    # ---------------------------------------------------------- lifecycle
    def close(self, unlink: bool = False) -> None:
        try:
            self._mm.close()
        except Exception:
            pass
        if unlink:
            try:
                os.unlink(self._path)
            except OSError:
                pass

    def __reduce__(self):
        return (StreamRing, (self.name, self.capacity, False))

    def spec(self) -> dict:
        """Wire form for request metadata (the consumer creates the ring,
        the producer attaches by spec)."""
        return {"name": self.name, "capacity": self.capacity}

    @classmethod
    def attach(cls, spec: dict) -> "StreamRing":
        return cls(spec["name"], int(spec["capacity"]), _create=False)
