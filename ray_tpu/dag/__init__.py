"""Compiled graphs: pre-wired actor pipelines over shm channels.

Parity target: reference python/ray/dag/compiled_dag_node.py:805
(experimental_compile — turn a bound DAG into persistent per-actor
execution loops connected by mutable shm channels, removing ALL per-call
RPC/scheduling from the steady state) + experimental/channel/.

Surface: function DAGs built with `.bind()`:

    with InputNode() as inp:
        dag = postprocess.bind(model_forward.bind(inp))
    cdag = compile(dag)           # stage actors + channels come up once
    out = cdag.execute(x)         # shm write -> pipeline -> shm read
    cdag.teardown()

Each DAG node becomes a dedicated stage ACTOR running a channel loop: the
driver writes the input channel and reads the output channel; intermediate
hops never touch the control plane. (The reference compiles existing-actor
method DAGs; stage actors are this round's functional equivalent for the
function-DAG surface.)
"""

from __future__ import annotations

import uuid
from typing import Any, Optional

import ray_tpu
from ray_tpu.experimental.channel import Channel
from ray_tpu.workflow import DAGNode


class InputNode:
    """Placeholder for the execute() argument (reference dag.InputNode)."""

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


class _StageActor:
    """Hosts one compiled stage: a loop pulling from the in-channel,
    applying the stage function, pushing to the out-channel."""

    def __init__(self, fn, in_name: str, out_name: str, size: int):
        self.fn = fn
        self.in_ch = Channel(in_name, size, _create=False)
        self.out_ch = Channel(out_name, size, _create=False)
        self._stop = False

    def run_loop(self):
        while True:
            try:
                item = self.in_ch.read(timeout=0.5)
            except TimeoutError:
                if self._stop:
                    return True
                continue
            if item is _SHUTDOWN or (isinstance(item, str) and item == "__rt_dag_stop__"):
                self.out_ch.write("__rt_dag_stop__")
                return True
            try:
                out = self.fn(item)
            except Exception as e:  # propagate downstream as an error value
                out = _StageError(repr(e))
            self.out_ch.write(out)

    def stop(self):
        self._stop = True
        return True


class _StageError:
    def __init__(self, msg: str):
        self.msg = msg


_SHUTDOWN = "__rt_dag_stop__"


def _linearize(dag: DAGNode) -> list:
    """Flatten a single-path function DAG (each node has exactly one
    DAGNode/InputNode arg) into stage order."""
    chain = []
    node: Any = dag
    while isinstance(node, DAGNode):
        dag_args = [a for a in list(node.args) + list(node.kwargs.values())
                    if isinstance(a, (DAGNode, InputNode))]
        if len(dag_args) != 1:
            raise ValueError(
                "compiled DAGs support linear function pipelines in this "
                "round (exactly one upstream per node)")
        chain.append(node)
        node = dag_args[0]
    if not isinstance(node, InputNode):
        raise ValueError("the pipeline root must consume InputNode")
    return list(reversed(chain))


class CompiledDAG:
    def __init__(self, dag: DAGNode, *, channel_size: int = 1 << 20):
        chain = _linearize(dag)
        tag = uuid.uuid4().hex[:8]
        n = len(chain)
        # channels: driver -> s0 -> s1 -> ... -> driver
        names = [f"{tag}_{i}" for i in range(n + 1)]
        self._channels = [Channel(nm, channel_size) for nm in names]
        self._in = self._channels[0]
        self._out = self._channels[-1]
        stage_cls = ray_tpu.remote(num_cpus=1, max_concurrency=2)(_StageActor)
        self._actors = []
        self._loops = []
        for i, node in enumerate(chain):
            fn = getattr(node.fn, "_fn", node.fn)
            a = stage_cls.remote(fn, names[i], names[i + 1], channel_size)
            self._actors.append(a)
            self._loops.append(a.run_loop.remote())
        self._dead = False

    def execute(self, value, timeout: float = 60.0):
        """One pipelined invocation: shm in, shm out — no per-call RPC."""
        assert not self._dead, "compiled DAG was torn down"
        self._in.write(value, timeout=timeout)
        out = self._out.read(timeout=timeout)
        if isinstance(out, _StageError):
            raise RuntimeError(f"compiled DAG stage failed: {out.msg}")
        return out

    def teardown(self):
        if self._dead:
            return
        self._dead = True
        try:
            self._in.write(_SHUTDOWN, timeout=5)
            ray_tpu.get(self._loops, timeout=30)
        except Exception:
            pass
        for a in self._actors:
            try:
                ray_tpu.kill(a)
            except Exception:
                pass
        for ch in self._channels:
            ch.close(unlink=True)


def compile(dag: DAGNode, **kw) -> CompiledDAG:  # noqa: A001 - reference name
    return CompiledDAG(dag, **kw)
