"""Compiled graphs: pre-wired execution over shm channels.

Parity target: reference python/ray/dag/compiled_dag_node.py:805
(experimental_compile — turn a bound DAG into persistent per-actor
execution loops connected by mutable shm channels, removing ALL per-call
RPC/scheduling from the steady state) + experimental/channel/.

Surface (general DAGs: fan-in, fan-out, multi-output, actor methods):

    with InputNode() as inp:
        a = f.bind(inp)                     # function stage
        b = my_actor.work.bind(inp)         # EXISTING actor's method stage
        dag = MultiOutputNode([g.bind(a, b), h.bind(a)])   # fan-in + fan-out
    cdag = compile(dag)
    out1, out2 = cdag.execute(x)            # shm in -> graph -> shm out
    cdag.teardown()

Every EDGE gets its own SPSC shm channel (a producer consumed by N
downstream nodes writes N channels — the fan-out mechanism; a node with
N upstream DAG args reads N channels — fan-in). Function nodes run in
dedicated stage actors; actor-method nodes attach an execution-loop
THREAD to the existing actor (reference: compiled loops on the bound
actors), so the steady state is channel reads/writes only — no RPC.
"""

from __future__ import annotations

import threading
import uuid
from typing import Any, Optional

import ray_tpu
from ray_tpu.dag.stream import RingClosed, StreamRing  # noqa: F401 (re-export)
from ray_tpu.experimental.channel import Channel
from ray_tpu.workflow import DAGNode

_SHUTDOWN = "__rt_dag_stop__"


class InputNode:
    """Placeholder for the execute() argument (reference dag.InputNode)."""

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


class MultiOutputNode:
    """Marks several DAG leaves as the compiled graph's outputs
    (reference dag.MultiOutputNode); execute() returns a list."""

    def __init__(self, nodes: list):
        self.nodes = list(nodes)


class ActorMethodNode(DAGNode):
    """A bound method of an EXISTING actor (reference: actor.method.bind).
    Created by ActorMethod.bind()."""

    def __init__(self, actor_handle, method_name: str, args, kwargs):
        super().__init__(None, args, kwargs, method_name)
        self.actor_handle = actor_handle
        self.method_name = method_name


class _StageError:
    def __init__(self, msg: str):
        self.msg = msg


def run_stage_loop(call, in_specs: list, out_names: list, kwargs: dict,
                   size: int):
    """The compiled execution loop shared by function-stage actors and
    actor-method loop threads: read every channel input, apply, write
    every out edge. Stop tokens and upstream stage errors pass through."""
    in_chs = [(i, Channel(nm, size, _create=False))
              for i, (kind, nm) in enumerate(in_specs) if kind == "ch"]
    literals = [v if kind == "lit" else None for kind, v in in_specs]
    out_chs = [Channel(nm, size, _create=False) for nm in out_names]
    while True:
        args = list(literals)
        stop = False
        err: Optional[_StageError] = None
        for i, ch in in_chs:
            item = ch.read(timeout=None)
            if isinstance(item, str) and item == _SHUTDOWN:
                stop = True
            elif isinstance(item, _StageError) and err is None:
                err = item
            else:
                args[i] = item
        if stop:
            for ch in out_chs:
                ch.write(_SHUTDOWN)
            return True
        if err is not None:
            out = err  # propagate the FIRST upstream error
        else:
            try:
                out = call(*args, **kwargs)
            except Exception as e:
                out = _StageError(repr(e))
        for ch in out_chs:
            ch.write(out)


class _StageActor:
    """Hosts one compiled FUNCTION stage."""

    def __init__(self, fn, in_specs: list, out_names: list, kwargs: dict,
                 size: int):
        self.fn = fn
        self.in_specs = in_specs
        self.out_names = out_names
        self.kwargs = kwargs
        self.size = size

    def run_loop(self):
        return run_stage_loop(self.fn, self.in_specs, self.out_names,
                              self.kwargs, self.size)


class CompiledDAG:
    def __init__(self, dag, *, channel_size: int = 1 << 20):
        outputs = dag.nodes if isinstance(dag, MultiOutputNode) else [dag]
        tag = uuid.uuid4().hex[:8]
        self._size = channel_size

        # ---- discover nodes + edges (consumer counts drive fan-out)
        nodes: list[DAGNode] = []
        seen: dict[int, DAGNode] = {}

        def visit(n):
            if isinstance(n, InputNode):
                return
            if id(n) in seen:
                return
            seen[id(n)] = n
            for a in list(n.args) + list(n.kwargs.values()):
                if isinstance(a, (DAGNode, InputNode)):
                    visit(a)
            nodes.append(n)  # post-order = topological

        for out in outputs:
            if not isinstance(out, DAGNode):
                raise ValueError("DAG outputs must be bound nodes")
            visit(out)

        # ---- one channel per EDGE
        self._channels: list[Channel] = []
        counter = [0]

        def new_channel() -> Channel:
            ch = Channel(f"{tag}_{counter[0]}", channel_size)
            counter[0] += 1
            self._channels.append(ch)
            return ch

        # producer node -> list of its out-edge channels
        out_edges: dict[int, list] = {id(n): [] for n in nodes}
        self._input_edges: list[Channel] = []  # driver-written
        # per node: in_specs aligned with positional args
        in_specs: dict[int, list] = {}
        kw_literals: dict[int, dict] = {}
        for n in nodes:
            specs = []
            for a in n.args:
                if isinstance(a, InputNode):
                    ch = new_channel()
                    self._input_edges.append(ch)
                    specs.append(("ch", ch.name))
                elif isinstance(a, DAGNode):
                    ch = new_channel()
                    out_edges[id(a)].append(ch)
                    specs.append(("ch", ch.name))
                else:
                    specs.append(("lit", a))
            kws = {}
            for k, a in n.kwargs.items():
                if isinstance(a, (DAGNode, InputNode)):
                    raise ValueError(
                        "DAG args must be positional (kwargs are literals)")
                kws[k] = a
            if not any(kind == "ch" for kind, _v in specs):
                # A node with no channel inputs would free-run decoupled
                # from execute() and its loop could never be stopped by
                # teardown (stop tokens flow along edges).
                raise ValueError(
                    f"DAG node {n.name!r} has no upstream: every node must "
                    f"consume InputNode or another node")
            in_specs[id(n)] = specs
            kw_literals[id(n)] = kws
        # output edges: driver-read
        self._output_edges: list[Channel] = []
        for out in outputs:
            ch = new_channel()
            out_edges[id(out)].append(ch)
            self._output_edges.append(ch)

        # ---- launch stages
        stage_cls = ray_tpu.remote(num_cpus=1, max_concurrency=2)(_StageActor)
        self._actors = []       # our function-stage actors (killed on teardown)
        self._loops = []
        self._actor_loop_refs = []  # existing-actor loop futures
        from ray_tpu._private.worker import global_worker

        for n in nodes:
            outs = [c.name for c in out_edges[id(n)]]
            if isinstance(n, ActorMethodNode):
                # Attach the loop to the EXISTING actor: a hidden actor task
                # the worker runtime runs on a dedicated thread (reference
                # compiled_dag_node attaches exec loops to bound actors).
                w = global_worker()
                refs = w.submit_actor_task(
                    n.actor_handle._actor_id, "__rt_dag_loop__",
                    ({"method": n.method_name,
                      "in_specs": in_specs[id(n)],
                      "out_names": outs,
                      "kwargs": kw_literals[id(n)],
                      "size": channel_size},), {})
                self._actor_loop_refs.append(refs[0])
            else:
                fn = getattr(n.fn, "_fn", n.fn)
                a = stage_cls.remote(fn, in_specs[id(n)], outs,
                                     kw_literals[id(n)], channel_size)
                self._actors.append(a)
                self._loops.append(a.run_loop.remote())
        self._multi = isinstance(dag, MultiOutputNode)
        self._dead = False

    def execute(self, value, timeout: float = 60.0):
        """One invocation: shm writes in, shm reads out — no per-call RPC.
        Returns the single output value, or a list for MultiOutputNode."""
        assert not self._dead, "compiled DAG was torn down"
        for ch in self._input_edges:
            ch.write(value, timeout=timeout)
        outs = [ch.read(timeout=timeout) for ch in self._output_edges]
        for o in outs:
            if isinstance(o, _StageError):
                raise RuntimeError(f"compiled DAG stage failed: {o.msg}")
        return outs if self._multi else outs[0]

    def teardown(self):
        if self._dead:
            return
        self._dead = True
        try:
            for ch in self._input_edges:
                ch.write(_SHUTDOWN, timeout=5)
            # drain the stop tokens so loops can finish their final writes
            for ch in self._output_edges:
                try:
                    ch.read(timeout=5)
                except Exception:
                    pass
            ray_tpu.get(self._loops + self._actor_loop_refs, timeout=30)
        except Exception:
            pass
        for a in self._actors:
            try:
                ray_tpu.kill(a)
            except Exception:
                pass
        for ch in self._channels:
            ch.close(unlink=True)


def compile(dag, **kw) -> CompiledDAG:  # noqa: A001 - reference name
    return CompiledDAG(dag, **kw)
