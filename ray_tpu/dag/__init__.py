"""Compiled dataflow graphs: a pipelined, zero-RPC execution plane.

Parity target: reference python/ray/dag/compiled_dag_node.py
(experimental_compile — turn a bound DAG into persistent per-actor
execution loops connected by mutable shm channels, removing ALL per-call
RPC/scheduling from the steady state) + experimental/channel/. This is the
substrate pipeline-/tensor-parallel inference needs: the owner and the
controller are out of the steady-state loop entirely.

Surface (general DAGs: fan-in, fan-out, multi-output, actor methods):

    with InputNode() as inp:
        a = f.bind(inp)                     # function stage
        b = my_actor.work.bind(inp)         # EXISTING actor's method stage
        dag = MultiOutputNode([g.bind(a, b), h.bind(a)])   # fan-in + fan-out
    cdag = compile(dag)
    ref = cdag.execute(x)                   # -> DagRef, returns immediately
    out1, out2 = ref.get(timeout=30)
    cdag.teardown()

The execution plane, in four pieces (README "Compiled graphs"):

- **Pipelined execution.** `execute()` returns a `DagRef` and keeps up to
  `RT_DAG_MAX_INFLIGHT` invocations in flight; a per-invocation sequence
  number rides every edge message, so stages stay in lockstep without any
  barrier (each edge is FIFO; a multi-input stage checks its inputs agree
  on the seq). A driver-side collector thread fulfills DagRefs in order.

- **Device-object edges** (`RT_DAG_DEVICE_EDGES`, default on). A stage
  output that is a large single-device `jax.Array` is pinned in the
  producing process's DeviceObjectTable (PR 7) and the channel carries
  only the ~200B placeholder; co-located consumers resolve it zero-copy
  (same process) or one-copy (same-host shm export) instead of paying a
  full pickle through the shm ring. Pins retire on a 2-invocation window:
  writing seq i requires every consumer to have acked seq i-1, which
  proves resolution of seq i-2 completed — so the producer frees i-2's
  pin without any consumer RPC. Off = byte-identical host path.

- **Attributed failure, never a hang.** Stage user-code exceptions ride
  the edges as `_StageError` (stage name + full remote traceback) and
  surface as a typed `DagStageError` on that invocation's DagRef only —
  the pipeline keeps flowing. Stage DEATH (actor SIGKILL, worker/node
  loss) is caught by the driver's liveness monitor watching every stage
  loop task: all in-flight DagRefs fail with a DagStageError naming the
  stage/node/invocation within the detection deadline, and
  `dag_compiled`/`dag_stage_death`/`dag_teardown` land in the PR 14 event
  plane. Stage loops tick PR 9 watchdog progress beacons while idle in
  channel waits, so an armed stall ladder never mistakes an idle stage
  for a wedged one. `teardown()` kills every stage loop THEN unlinks
  every channel unconditionally — no shm segment outlives the graph.

- **Tracing.** When the PR 11 plane samples an invocation, a
  `dag.execute` span (submit -> fulfillment) roots per-stage `dag.stage`
  spans; the TraceContext rides the edge messages.

Every EDGE gets its own SPSC shm channel (a producer consumed by N
downstream nodes writes N channels — the fan-out mechanism; a node with
N upstream DAG args reads N channels — fan-in). Function nodes run in
dedicated stage actors; actor-method nodes attach an execution-loop
THREAD to the existing actor (reference: compiled loops on the bound
actors), so the steady state is channel reads/writes only — no RPC.
"""

from __future__ import annotations

import os
import threading
import time
import traceback as _tb
import uuid
from typing import Any, Optional

import ray_tpu
from ray_tpu import exceptions as exc
from ray_tpu._private import events as _events
from ray_tpu._private import tracing as _tracing
from ray_tpu._private import watchdog as _watchdog
from ray_tpu._private.ids import random_id_bytes
from ray_tpu._private.rtconfig import CONFIG
from ray_tpu.dag.stream import RingClosed, StreamRing  # noqa: F401 (re-export)
from ray_tpu.exceptions import DagStageError  # noqa: F401 (re-export)
from ray_tpu.experimental.channel import Channel
from ray_tpu.workflow import DAGNode

_SHUTDOWN = "__rt_dag_stop__"
_CANCELLED = object()  # edge-op sentinel: the hosting loop was cancelled


class InputNode:
    """Placeholder for the execute() argument (reference dag.InputNode)."""

    def __enter__(self):
        return self

    def __exit__(self, *exc_info):
        return False


class MultiOutputNode:
    """Marks several DAG leaves as the compiled graph's outputs
    (reference dag.MultiOutputNode); DagRef.get() returns a list."""

    def __init__(self, nodes: list):
        self.nodes = list(nodes)


class ActorMethodNode(DAGNode):
    """A bound method of an EXISTING actor (reference: actor.method.bind).
    Created by ActorMethod.bind()."""

    def __init__(self, actor_handle, method_name: str, args, kwargs):
        super().__init__(None, args, kwargs, method_name)
        self.actor_handle = actor_handle
        self.method_name = method_name


class _StageError:
    """A stage's user-code failure riding the edges to the outputs: names
    the stage and carries the FULL formatted remote traceback (surfaced as
    DagStageError at DagRef.get)."""

    __slots__ = ("stage", "msg", "traceback_str")

    def __init__(self, stage: str, msg: str, traceback_str: str = ""):
        self.stage = stage
        self.msg = msg
        self.traceback_str = traceback_str


# --------------------------------------------------------------- edge ops
def _edge_read(ch: Channel, stop: Optional[threading.Event],
               timeout: Optional[float] = None):
    """Read one edge message in stop-checked, beacon-ticking slices: an
    IDLE stage parked here is alive (its watchdog beacon keeps ticking),
    and a cancelled loop (teardown after a peer death) exits promptly
    instead of blocking forever on a dead producer."""
    deadline = None if timeout is None else time.monotonic() + timeout
    while True:
        if stop is not None and stop.is_set():
            return _CANCELLED
        try:
            return ch.read(timeout=_watchdog.progress_slice_s())
        except TimeoutError:
            _watchdog.report_progress()
            if deadline is not None and time.monotonic() > deadline:
                raise


def _edge_write(ch: Channel, value, stop: Optional[threading.Event],
                timeout: Optional[float] = None) -> Optional[object]:
    """Write one edge message under the same slicing discipline (the
    consumer may be backpressuring us for a while — that is pipelining,
    not a stall). Returns _CANCELLED if the loop was stopped mid-wait."""
    deadline = None if timeout is None else time.monotonic() + timeout
    while True:
        if stop is not None and stop.is_set():
            return _CANCELLED
        try:
            ch.write(value, timeout=_watchdog.progress_slice_s())
            return None
        except TimeoutError:
            _watchdog.report_progress()
            if deadline is not None and time.monotonic() > deadline:
                raise


class _EdgePublisher:
    """Device-object edge encoder (one per producing node, one for the
    driver's input edges): large single-device jax.Arrays — bare or inside
    a tuple/list stage output (iterative graphs carry (tag, activation,
    meta) messages) — are pinned locally and replaced by the ~200B
    tier-ladder placeholder; everything else passes through untouched.
    Each pinned array is also eagerly EXPORTED into the local shm store at
    publish time: the export precedes the channel write, so a same-host
    consumer's resolve is a store hit — zero RPCs in the steady state —
    instead of an export_device_object round trip back to the producer.
    Pins retire on the 2-invocation window proved safe by channel
    backpressure (module docstring); retirement is grouped per publish so
    multi-array messages keep the same window."""

    __slots__ = ("_pins", "_on", "_min_bytes")

    def __init__(self):
        self._pins: list[list[str]] = []  # oldest first; one group/publish
        self._on: Optional[bool] = None
        self._min_bytes: Optional[int] = None

    def _enabled(self) -> bool:
        on = self._on
        if on is None:
            try:
                on = bool(CONFIG.dag_device_edges)
            except Exception:
                on = True
            self._on = on
        return on

    def publish(self, value):
        if not self._enabled():
            return value
        if self._min_bytes is None:
            try:
                self._min_bytes = int(CONFIG.dag_edge_min_bytes)
            except Exception:
                self._min_bytes = 1024
        group: list[str] = []
        out = self._pub(value, group, depth=0)
        self._pins.append(group)
        return out

    def _pub(self, value, group: list, depth: int):
        if depth < 2 and type(value) in (tuple, list):
            items = [self._pub(v, group, depth + 1) for v in value]
            return tuple(items) if type(value) is tuple else items
        from ray_tpu._private import device_store

        if not device_store.eligible(value, min_bytes=self._min_bytes):
            return value
        from ray_tpu._private.worker import global_worker

        w = global_worker()
        if w is None:
            return value
        oid = random_id_bytes(16).hex()
        ref = device_store.pin_edge(oid, value, w)
        if w.store is not None:
            try:
                # Eager same-host export: one host copy now (the lazy path
                # pays the same copy at first consumer RPC) buys every
                # consumer an RPC-free store-hit resolve.
                device_store.export_to_store(oid, w.store)
            except Exception:
                pass  # consumers fall back to the export-RPC tier
        group.append(oid)
        return ref

    def retire(self, keep: int = 2) -> None:
        while len(self._pins) > keep:
            self._free(self._pins.pop(0))

    def close(self) -> None:
        while self._pins:
            self._free(self._pins.pop())

    @staticmethod
    def _free(oids: list) -> None:
        if not oids:
            return
        try:
            from ray_tpu._private import device_store
            from ray_tpu._private.worker import global_worker

            w = global_worker()
            device_store.free_local(oids, store=w.store if w else None)
        except Exception:
            pass  # process-exit frees are the backstop


# ------------------------------------------------------------- stage loop
def run_stage_loop(call, in_specs: list, out_names: list, kwargs: dict,
                   size: int, *, stage: str = "stage",
                   stop: Optional[threading.Event] = None):
    """The compiled execution loop shared by function-stage actors and
    actor-method loop threads: read every channel input, check lockstep,
    apply, publish every out edge. Stop tokens and upstream stage errors
    pass through; each message is (seq, trace_ctx, value). Returns True on
    a clean stop-token shutdown, False when cancelled via `stop`."""
    in_chs = [(i, Channel(nm, size, _create=False))
              for i, (kind, nm) in enumerate(in_specs) if kind == "ch"]
    literals = [v if kind == "lit" else None for kind, v in in_specs]
    out_chs = [Channel(nm, size, _create=False) for nm in out_names]
    pub = _EdgePublisher()
    try:
        while True:
            args = list(literals)
            stop_tok = False
            err: Optional[_StageError] = None
            seq = None
            ctx = None
            for i, ch in in_chs:
                item = _edge_read(ch, stop)
                if item is _CANCELLED:
                    return False
                if isinstance(item, str) and item == _SHUTDOWN:
                    stop_tok = True
                    continue
                iseq, ictx, val = item
                if seq is None:
                    seq = iseq
                elif iseq != seq and err is None:
                    # FIFO edges make this unreachable in a healthy graph;
                    # it guards channel corruption from turning into
                    # silently mismatched invocations.
                    err = _StageError(
                        stage, f"lockstep violation: edge delivered seq "
                               f"{iseq} while a sibling delivered {seq}")
                if ictx is not None:
                    ctx = ictx
                if isinstance(val, _StageError):
                    if err is None:
                        err = val  # propagate the FIRST upstream error
                else:
                    args[i] = val
            if stop_tok:
                for ch in out_chs:
                    try:
                        _edge_write(ch, _SHUTDOWN, stop, timeout=5)
                    except TimeoutError:
                        pass  # dead/slow peer: teardown unlinks regardless
                return True
            if err is not None:
                out: Any = err
            else:
                t0 = time.time()
                try:
                    out = call(*args, **kwargs)
                except Exception as e:
                    out = _StageError(stage, f"{type(e).__name__}: {e}",
                                      _tb.format_exc())
                if ctx is not None:
                    _tracing.record_span_in(
                        tuple(ctx), "dag.stage", "dag", t0, time.time(),
                        {"stage": stage, "seq": seq,
                         "ok": not isinstance(out, _StageError)})
            wire = pub.publish(out) if not isinstance(out, _StageError) else out
            for ch in out_chs:
                if _edge_write(ch, (seq, ctx, wire), stop) is _CANCELLED:
                    return False
            # Every consumer acked seq-1 for these writes to complete, so
            # resolution of seq-2 provably finished: retire older pins.
            pub.retire(keep=2)
    finally:
        pub.close()
        for _i, ch in in_chs:
            ch.close()
        for ch in out_chs:
            ch.close()
        # Final act: force-drain this process's span/event rings — the
        # driver kills stage actors shortly after the loop exits, and a
        # kill landing between 1 Hz flush ticks would silently eat the
        # last invocations' dag.stage spans.
        try:
            from ray_tpu.util import metrics

            metrics.flush_on_shutdown()
        except Exception:
            pass


class _StageActor:
    """Hosts one compiled FUNCTION stage."""

    def __init__(self, fn, in_specs: list, out_names: list, kwargs: dict,
                 size: int, stage: str):
        self.fn = fn
        self.in_specs = in_specs
        self.out_names = out_names
        self.kwargs = kwargs
        self.size = size
        self.stage = stage

    def run_loop(self):
        return run_stage_loop(self.fn, self.in_specs, self.out_names,
                              self.kwargs, self.size, stage=self.stage)

    def pid(self):
        import os

        return os.getpid()

    def probe(self) -> dict:
        """Introspection for tests/ops: this stage process's device-object
        residency (device-edge pins live here)."""
        from ray_tpu._private import device_store

        return device_store.table_stats()


# ----------------------------------------------------------------- driver
class DagRef:
    """Handle to one in-flight compiled-DAG invocation. `get()` blocks for
    the result; a stage failure raises the typed DagStageError naming the
    stage (and the full remote traceback for user-code errors)."""

    __slots__ = ("seq", "_event", "_value", "_error")

    def __init__(self, seq: int):
        self.seq = seq
        self._event = threading.Event()
        self._value = None
        self._error: Optional[BaseException] = None

    def done(self) -> bool:
        return self._event.is_set()

    def get(self, timeout: Optional[float] = 60.0):
        if not self._event.wait(timeout):
            raise exc.GetTimeoutError(
                f"compiled-DAG invocation {self.seq} not fulfilled within "
                f"{timeout}s")
        if self._error is not None:
            raise self._error
        return self._value


class _Stage:
    """Driver-side bookkeeping for one stage loop."""

    __slots__ = ("name", "kind", "ref", "actor_id", "handle", "settled")

    def __init__(self, name: str, kind: str, ref, actor_id: str, handle):
        self.name = name
        self.kind = kind          # "stage_actor" | "actor_method"
        self.ref = ref            # the loop task's ObjectRef
        self.actor_id = actor_id
        self.handle = handle      # ActorHandle (stage actors only)
        self.settled = False


class CompiledDAG:
    def __init__(self, dag, *, channel_size: Optional[int] = None):
        outputs = dag.nodes if isinstance(dag, MultiOutputNode) else [dag]
        tag = uuid.uuid4().hex[:8]
        if channel_size is None:
            channel_size = int(CONFIG.dag_channel_bytes)
        self._size = channel_size
        self._tag = tag
        self.dag_id = f"dag-{tag}"

        # ---- discover nodes + edges (consumer counts drive fan-out)
        nodes: list[DAGNode] = []
        seen: dict[int, DAGNode] = {}

        def visit(n):
            if isinstance(n, InputNode):
                return
            if id(n) in seen:
                return
            seen[id(n)] = n
            for a in list(n.args) + list(n.kwargs.values()):
                if isinstance(a, (DAGNode, InputNode)):
                    visit(a)
            nodes.append(n)  # post-order = topological

        for out in outputs:
            if not isinstance(out, DAGNode):
                raise ValueError("DAG outputs must be bound nodes")
            visit(out)

        # ---- one channel per EDGE
        self._channels: list[Channel] = []
        counter = [0]

        def new_channel() -> Channel:
            ch = Channel(f"{tag}_{counter[0]}", channel_size)
            counter[0] += 1
            self._channels.append(ch)
            return ch

        # producer node -> list of its out-edge channels
        out_edges: dict[int, list] = {id(n): [] for n in nodes}
        self._input_edges: list[Channel] = []  # driver-written
        # per node: in_specs aligned with positional args
        in_specs: dict[int, list] = {}
        kw_literals: dict[int, dict] = {}
        stage_names: dict[int, str] = {}
        for idx, n in enumerate(nodes):
            stage_names[id(n)] = f"{n.name}[{idx}]"
            specs = []
            for a in n.args:
                if isinstance(a, InputNode):
                    ch = new_channel()
                    self._input_edges.append(ch)
                    specs.append(("ch", ch.name))
                elif isinstance(a, DAGNode):
                    ch = new_channel()
                    out_edges[id(a)].append(ch)
                    specs.append(("ch", ch.name))
                else:
                    specs.append(("lit", a))
            kws = {}
            for k, a in n.kwargs.items():
                if isinstance(a, (DAGNode, InputNode)):
                    raise ValueError(
                        "DAG args must be positional (kwargs are literals)")
                kws[k] = a
            if not any(kind == "ch" for kind, _v in specs):
                # A node with no channel inputs would free-run decoupled
                # from execute() and its loop could never be stopped by
                # teardown (stop tokens flow along edges).
                raise ValueError(
                    f"DAG node {n.name!r} has no upstream: every node must "
                    f"consume InputNode or another node")
            in_specs[id(n)] = specs
            kw_literals[id(n)] = kws
        # output edges: driver-read
        self._output_edges: list[Channel] = []
        for out in outputs:
            ch = new_channel()
            out_edges[id(out)].append(ch)
            self._output_edges.append(ch)

        # ---- launch stages
        stage_cls = ray_tpu.remote(num_cpus=0, max_concurrency=2)(_StageActor)
        self._actors = []       # our function-stage actors (killed on teardown)
        self._stages: list[_Stage] = []
        from ray_tpu._private.worker import global_worker

        try:
            for n in nodes:
                outs = [c.name for c in out_edges[id(n)]]
                name = stage_names[id(n)]
                if isinstance(n, ActorMethodNode):
                    # Attach the loop to the EXISTING actor: a hidden actor
                    # task the worker runtime runs on a dedicated thread
                    # (reference compiled_dag_node attaches exec loops to
                    # bound actors).
                    w = global_worker()
                    refs = w.submit_actor_task(
                        n.actor_handle._actor_id, "__rt_dag_loop__",
                        ({"method": n.method_name,
                          "in_specs": in_specs[id(n)],
                          "out_names": outs,
                          "kwargs": kw_literals[id(n)],
                          "size": channel_size,
                          "stage": name,
                          "tag": tag},), {})
                    self._stages.append(_Stage(
                        name, "actor_method", refs[0],
                        n.actor_handle._actor_id, n.actor_handle))
                else:
                    fn = getattr(n.fn, "_fn", n.fn)
                    a = stage_cls.remote(fn, in_specs[id(n)], outs,
                                         kw_literals[id(n)], channel_size,
                                         name)
                    self._actors.append(a)
                    self._stages.append(_Stage(
                        name, "stage_actor", a.run_loop.remote(),
                        a._actor_id, a))
        except BaseException:
            # Compile failed mid-launch: the caller never gets an object to
            # teardown, so nothing else would ever unlink these segments.
            for a in self._actors:
                try:
                    ray_tpu.kill(a)
                except Exception:
                    pass
            for ch in self._channels:
                try:
                    ch.close(unlink=True)
                except Exception:
                    pass
            raise
        self._multi = isinstance(dag, MultiOutputNode)

        # ---- pipelined-driver state
        self._dead = False
        self._dead_error: Optional[DagStageError] = None
        self._torn = False
        self._tearing_down = False
        self._stop = threading.Event()
        self._lock = threading.Lock()          # pending + death transitions
        self._submit_lock = threading.Lock()   # seq order == edge FIFO order
        self._pending: dict[int, tuple] = {}   # seq -> (DagRef, trace handle)
        self._next_seq = 0
        self._inflight = threading.Semaphore(max(1, int(CONFIG.dag_max_inflight)))
        self._publisher = _EdgePublisher()
        # Submission queue: execute() enqueues and returns; the feeder
        # thread pays the input edges' (capacity-1) backpressure, so the
        # driver really does keep RT_DAG_MAX_INFLIGHT invocations in
        # flight instead of being throttled to the first stage's pace.
        self._submit_q: list = []
        self._submit_cv = threading.Condition()
        self._feeder = threading.Thread(
            target=self._feed_loop, daemon=True, name="rt-dag-feed")
        self._feeder.start()
        self._collector = threading.Thread(
            target=self._collect_loop, daemon=True, name="rt-dag-collect")
        self._collector.start()
        self._monitor = threading.Thread(
            target=self._monitor_loop, daemon=True, name="rt-dag-monitor")
        self._monitor.start()
        _events.emit_event(
            "dag_compiled",
            f"compiled DAG {self.dag_id}: {len(nodes)} stages, "
            f"{counter[0]} channels",
            entity=[self.dag_id],
            attrs={"stages": len(nodes), "channels": counter[0]})

    # ------------------------------------------------------------ execute
    def execute(self, value, timeout: float = 60.0) -> DagRef:
        """One invocation: shm writes in, a DagRef back — no per-call RPC.
        Returns immediately while fewer than RT_DAG_MAX_INFLIGHT
        invocations are unfulfilled; beyond that (or under stage
        backpressure) it blocks up to `timeout`. DagRef.get() returns the
        single output value, or a list for MultiOutputNode."""
        self._check_alive()
        if not self._inflight.acquire(timeout=timeout):
            raise exc.GetTimeoutError(
                f"compiled DAG {self.dag_id}: {CONFIG.dag_max_inflight} "
                f"invocations already in flight and none completed within "
                f"{timeout}s")
        acquired = True
        try:
            with self._submit_lock:
                seq = self._next_seq
                self._next_seq += 1
                handle = _tracing.open_root("dag.execute", "dag")
                ctx = (handle[0], handle[1]) if handle is not None else None
                ref = DagRef(seq)
                with self._lock:
                    # Re-checked under the SAME lock _fail_with/teardown
                    # sweep _pending with: a ref registered after the
                    # sweep would never be fulfilled — get(timeout=None)
                    # would hang, violating the never-a-hang contract.
                    self._check_alive()
                    self._pending[seq] = (ref, handle)
                acquired = False  # the collector (or _fail) releases now
                with self._submit_cv:
                    self._submit_q.append((seq, ctx, value))
                    self._submit_cv.notify()
            return ref
        finally:
            if acquired:
                self._inflight.release()

    def _feed_loop(self) -> None:
        """Write queued invocations into the input edges in seq order —
        the single writer, so FIFO holds. A _SHUTDOWN marker (graceful
        teardown) forwards stop tokens BEHIND every queued invocation. Any
        submission failure (e.g. a value larger than RT_DAG_CHANNEL_BYTES)
        kills the graph attributed — a silently dead feeder would strand
        every already-returned DagRef."""
        try:
            while True:
                with self._submit_cv:
                    while not self._submit_q:
                        if self._stop.is_set():
                            return
                        self._submit_cv.wait(timeout=0.2)
                    item = self._submit_q.pop(0)
                if isinstance(item, str) and item == _SHUTDOWN:
                    for ch in self._input_edges:
                        try:
                            _edge_write(ch, _SHUTDOWN, self._stop, timeout=10)
                        except TimeoutError:
                            pass  # dead/slow stage: the kill path handles it
                    return
                seq, ctx, value = item
                wire = self._publisher.publish(value)
                for ch in self._input_edges:
                    if _edge_write(ch, (seq, ctx, wire),
                                   self._stop) is _CANCELLED:
                        return
                self._publisher.retire(keep=2)
        except Exception as e:
            if not (self._stop.is_set() or self._tearing_down):
                self._fail(DagStageError(
                    f"compiled DAG {self.dag_id}: input submission failed "
                    f"({type(e).__name__}: {e})"))

    def _check_alive(self) -> None:
        if self._torn:
            raise RuntimeError("compiled DAG was torn down")
        if self._dead:
            raise self._dag_error()

    def _dag_error(self) -> DagStageError:
        err = self._dead_error
        if err is None:
            err = DagStageError(f"compiled DAG {self.dag_id} is dead")
        return err

    # ---------------------------------------------------------- collector
    def _collect_loop(self) -> None:
        """Read output edges in invocation order and fulfill DagRefs —
        the only consumer of the output channels, so seqs arrive FIFO."""
        try:
            while not self._stop.is_set():
                outs = []
                seq = None
                for ch in self._output_edges:
                    item = _edge_read(ch, self._stop)
                    if item is _CANCELLED:
                        return
                    if isinstance(item, str) and item == _SHUTDOWN:
                        return
                    iseq, _ictx, val = item
                    if seq is None:
                        seq = iseq
                    elif iseq != seq:
                        raise DagStageError(
                            f"compiled DAG {self.dag_id}: output edges "
                            f"disagree on invocation ({iseq} vs {seq})")
                    outs.append(val)
                self._fulfill(seq, outs)
        except Exception as e:  # a dead graph must never hang consumers
            if not (self._stop.is_set() or self._tearing_down):
                self._fail(DagStageError(
                    f"compiled DAG {self.dag_id}: result collection failed "
                    f"({type(e).__name__}: {e})"))

    def _fulfill(self, seq: int, outs: list) -> None:
        with self._lock:
            ent = self._pending.pop(seq, None)
        if ent is None:
            return  # already failed by the monitor
        ref, handle = ent
        errs = [v for v in outs if isinstance(v, _StageError)]
        if errs:
            e = errs[0]
            msg = (f"compiled DAG stage {e.stage!r} failed on invocation "
                   f"{seq}: {e.msg}")
            if e.traceback_str:
                msg += "\n" + e.traceback_str
            ref._error = DagStageError(msg, stage=e.stage, invocation=seq,
                                       traceback_str=e.traceback_str)
        else:
            ref._value = outs if self._multi else outs[0]
        _tracing.close_root(handle, {"seq": seq, "ok": not errs})
        ref._event.set()
        self._inflight.release()

    # ------------------------------------------------------------ monitor
    def _monitor_loop(self) -> None:
        """Stage-liveness watch: a loop task that settles BEFORE teardown
        (actor death, leased-worker death, channel peer gone — or an
        unexpected clean exit) kills the graph with an attributed error on
        every in-flight DagRef. Detection deadline = the runtime's own
        death-detection latency + one monitor poll."""
        try:
            interval = max(0.05, float(CONFIG.dag_monitor_interval_s))
        except Exception:
            interval = 0.2
        while not self._stop.wait(interval):
            for st in self._stages:
                if st.settled:
                    continue
                try:
                    done, _ = ray_tpu.wait([st.ref], num_returns=1,
                                           timeout=0.05)
                except Exception:
                    return  # driver runtime is shutting down
                if not done:
                    continue
                st.settled = True
                if self._tearing_down or self._stop.is_set():
                    continue
                try:
                    ray_tpu.get(st.ref, timeout=5)
                    cause = "stage loop exited unexpectedly"
                except Exception as e:
                    cause = f"{type(e).__name__}: {e}"
                self._on_stage_death(st, cause)
                return

    def _stage_node(self, st: _Stage) -> Optional[str]:
        """Best-effort: which node the (dead) stage lived on."""
        try:
            from ray_tpu.util import state

            for row in state.list_actors():
                if row.get("actor_id") == st.actor_id:
                    return row.get("node_id") or row.get("node")
        except Exception:
            pass
        return None

    def _on_stage_death(self, st: _Stage, cause: str) -> None:
        node = self._stage_node(st)
        with self._lock:
            seqs = sorted(self._pending)
        _events.emit_event(
            "dag_stage_death",
            f"compiled DAG {self.dag_id}: stage {st.name!r} died "
            f"({cause}); {len(seqs)} invocation(s) in flight",
            entity=[self.dag_id, st.actor_id],
            attrs={"stage": st.name, "cause": cause,
                   "node": node, "inflight": len(seqs)})

        def mk(seq: Optional[int]) -> DagStageError:
            return DagStageError(
                f"compiled DAG {self.dag_id}: stage {st.name!r}"
                f"{f' on node {node[:12]}' if node else ''} died mid-run "
                f"({cause})"
                + (f"; invocation {seq} was in flight" if seq is not None
                   else ""),
                stage=st.name, node=node, invocation=seq)

        self._fail_with(mk)

    def _fail(self, err: DagStageError) -> None:
        self._fail_with(lambda seq: DagStageError(
            str(err), stage=err.stage, node=err.node, invocation=seq,
            traceback_str=err.traceback_str))

    def _fail_with(self, make_err) -> None:
        """Kill the graph: every in-flight DagRef resolves to an attributed
        error NOW (never a hang), later execute() calls raise the same."""
        with self._lock:
            if self._dead:
                return
            self._dead = True
            self._dead_error = make_err(None)
            pending = sorted(self._pending.items())
            self._pending.clear()
        self._stop.set()
        for seq, (ref, handle) in pending:
            ref._error = make_err(seq)
            _tracing.close_root(handle, {"seq": seq, "ok": False})
            ref._event.set()
            self._inflight.release()

    # ------------------------------------------------------------ teardown
    def teardown(self) -> None:
        """Stop every stage loop, then unlink every channel — both
        UNCONDITIONALLY (a stage dead mid-run leaves peers parked on its
        edges; they are killed/cancelled rather than waited on, and no shm
        segment survives regardless of how the graph ended)."""
        with self._lock:
            if self._torn:
                return
            self._torn = True
        self._tearing_down = True
        clean = not self._dead
        loop_refs = [st.ref for st in self._stages]
        if clean:
            # Graceful path: a stop marker rides the submission queue, so
            # the feeder forwards stop tokens BEHIND every queued
            # invocation and outstanding DagRefs still fulfill before the
            # collector reads the shutdown marker.
            with self._submit_cv:
                self._submit_q.append(_SHUTDOWN)
                self._submit_cv.notify()
            self._feeder.join(timeout=15)
            if self._feeder.is_alive():
                clean = False  # a stage stopped consuming: kill path below
            try:
                ray_tpu.wait(loop_refs, num_returns=len(loop_refs),
                             timeout=10)
            except Exception:
                pass
        self._stop.set()
        # Cooperative cancel for loops attached to EXISTING actors (the
        # actor itself survives teardown; only its loop thread must exit —
        # its upstream may be dead, so the stop token may never arrive).
        from ray_tpu._private.worker import global_worker

        w = global_worker()
        for st in self._stages:
            if st.kind == "actor_method" and not st.settled and w is not None:
                try:
                    w.submit_actor_task(st.actor_id, "__rt_dag_cancel__",
                                        ({"tag": self._tag},), {})
                except Exception:
                    pass
        # Kill-then-unlink: stage actors die unconditionally...
        for a in self._actors:
            try:
                ray_tpu.kill(a)
            except Exception:
                pass
        try:
            # ...and we wait for every loop to settle so a straggler can't
            # race the unlink below (strict channel attach backstops this).
            ray_tpu.wait(loop_refs, num_returns=len(loop_refs), timeout=10)
        except Exception:
            pass
        # The feeder/collector must be OUT of their channel ops before the
        # mmaps close: a native futex wait on a just-closed mapping is a
        # segfault, not an exception. Both exit within one stop-checked
        # slice of _stop being set.
        self._feeder.join(timeout=5)
        self._collector.join(timeout=5)
        threads_done = not (self._feeder.is_alive()
                            or self._collector.is_alive())
        # Fail anything still unresolved (torn down with work in flight).
        with self._lock:
            pending = sorted(self._pending.items())
            self._pending.clear()
        for seq, (ref, handle) in pending:
            if ref._event.is_set():
                continue
            ref._error = DagStageError(
                f"compiled DAG {self.dag_id} was torn down with invocation "
                f"{seq} in flight", invocation=seq)
            _tracing.close_root(handle, {"seq": seq, "ok": False})
            ref._event.set()
        # ...then every channel unlinks, no matter what came before. If a
        # driver thread would not settle, unlink the NAME only — the
        # segment is gone from /dev/shm either way, and the mapping dies
        # with the process instead of under a thread still waiting on it.
        self._publisher.close()
        for ch in self._channels:
            try:
                if threads_done:
                    ch.close(unlink=True)
                else:
                    os.unlink(ch._path)
            except OSError:
                pass
            except Exception:
                pass
        _events.emit_event(
            "dag_teardown",
            f"compiled DAG {self.dag_id} torn down "
            f"({'clean' if clean else 'forced'})",
            entity=[self.dag_id], attrs={"clean": clean})
        self._monitor.join(timeout=5)


def compile(dag, **kw) -> CompiledDAG:  # noqa: A001 - reference name
    return CompiledDAG(dag, **kw)
