"""Push-stream: the StreamRing record contract generalized onto rpc.

README "Cross-host streaming & multi-proxy": a replica on another host
cannot attach the proxy's /dev/shm StreamRing, and before this module
existed it nakked the handshake and degraded to the per-item classic
reply path — one ObjectRef round trip per token batch. The push-stream
keeps the ring's contract (variable-length pickled records, bounded
producer-side buffering, batch-per-wakeup consumer drains, RingClosed at
end-of-stream) but carries the records over the rpc transport:

- **producer** (`PushStreamWriter`, replica side): `write(value,
  timeout)` appends a record to a bounded send window; a dedicated flush
  task coalesces every record buffered since the last flush into ONE
  `s_data` frame (the PR 3 write-coalescing idiom, one level up the
  stack). The window is credit-based: at most `window` un-acked record
  bytes may be in flight, and a stalled consumer parks the writer —
  bounded buffering, never unbounded queueing, exactly like a full ring.
- **consumer** (`PushStreamHub` + `PushStreamReader`, proxy side): one
  rpc server per proxy process; frames route by stream id to a reader
  whose `read_batch(timeout)` drains every buffered record in one wakeup
  and credits the drained bytes back to the producer.

Fault attribution: frames carry per-stream sequence numbers, so a
duplicated frame is discarded (byte-identical outcome) and a dropped
frame is detected as a gap and surfaces as `StreamSevered` (attributed
outcome) — never silent corruption. A severed connection (replica death,
injected sever) also raises `StreamSevered` on the reader and wakes any
parked writer. The FaultInjector sees these connections under the
label "stream".
"""

from __future__ import annotations

import asyncio
import pickle
import threading
import time
from collections import deque
from typing import Optional

from ray_tpu.dag.stream import RingClosed

#: FaultInjector connection class for every push-stream link.
STREAM_LABEL = "stream"


class StreamSevered(Exception):
    """The stream link was lost (connection closed or a frame gap was
    detected) before the producer's end-of-stream record arrived."""


def _mint(records: int, nbytes: int) -> None:
    """Producer-side metric mints (counters ride the existing flusher)."""
    try:
        from ray_tpu.util import metrics as _m

        _m.STREAM_PUSH_RECORDS.inc(records)
        _m.STREAM_PUSH_BYTES.inc(nbytes)
    except Exception:
        pass


def _mint_park() -> None:
    try:
        from ray_tpu.util import metrics as _m

        _m.STREAM_PUSH_PARKS.inc(1)
    except Exception:
        pass


# --------------------------------------------------------------- consumer
class PushStreamReader:
    """Consumer end of one push-stream: the proxy's drain loop calls
    `read_batch` from an executor thread (same calling convention as
    StreamRing.read_batch), frames arrive on the hub's event loop."""

    def __init__(self, hub: "PushStreamHub", stream_id: str, window: int):
        self._hub = hub
        self.stream_id = stream_id
        self.window = window
        self._recs: deque = deque()  # (blob_len, value)
        self._cond = threading.Condition()
        self._conn = None  # producer's connection, set at s_open
        self._expect_seq = 0
        self._closed = False  # producer sent s_close (clean end)
        self._severed: Optional[str] = None  # link lost / frame gap

    # -- hub side (event-loop thread) -------------------------------------
    def _on_open(self, conn) -> None:
        with self._cond:
            self._conn = conn
            self._cond.notify_all()

    def _on_data(self, seq: int, blobs: list) -> None:
        with self._cond:
            if self._severed is not None:
                return  # stream already attributed dead: drop strays
            # NOTE: records arriving around s_close are NOT dropped — the
            # reader raises RingClosed only once everything is drained.
            if seq < self._expect_seq:
                return  # duplicated frame (injected dup / resend): discard
            if seq > self._expect_seq:
                # A frame was lost on the wire: the byte stream can no
                # longer be reproduced — attribute, never silently skip.
                self._severed = (f"push-stream frame gap (expected seq "
                                 f"{self._expect_seq}, got {seq})")
                self._cond.notify_all()
                return
            self._expect_seq += 1
            for b in blobs:
                self._recs.append((len(b), pickle.loads(b)))
            self._cond.notify_all()

    def _on_close_conn(self) -> None:
        with self._cond:
            if not self._closed and self._severed is None:
                self._severed = "push-stream connection severed"
            self._cond.notify_all()

    def _on_stream_close(self, seq: Optional[int] = None) -> None:
        with self._cond:
            if (seq is not None and seq != self._expect_seq
                    and self._severed is None):
                # s_close carries the producer's final frame count: a tail
                # frame lost on the wire has no successor to expose its
                # gap, so the close record is what catches it — silent
                # truncation is never a clean end.
                self._severed = (f"push-stream lost tail frames (expected "
                                 f"seq {self._expect_seq}, producer sent "
                                 f"{seq})")
            self._closed = True
            self._cond.notify_all()

    # -- proxy side (executor thread) -------------------------------------
    def read_batch(self, timeout: float | None = None,
                   max_bytes: int | None = None) -> list:
        """Block until at least one record arrived, then return every
        buffered record (one wakeup drains the burst) and credit the
        drained bytes back to the producer. Raises TimeoutError when
        nothing arrives in time, RingClosed once the producer closed and
        everything is drained, StreamSevered on a lost link/frame."""
        deadline = (None if timeout is None
                    else time.monotonic() + timeout)
        with self._cond:
            while not self._recs:
                if self._severed is not None:
                    raise StreamSevered(self._severed)
                if self._closed:
                    raise RingClosed("push stream closed and drained")
                left = (None if deadline is None
                        else deadline - time.monotonic())
                if left is not None and left <= 0:
                    raise TimeoutError("push stream read timed out")
                self._cond.wait(timeout=left)
            out = []
            drained = 0
            budget = max_bytes if max_bytes is not None else float("inf")
            while self._recs and drained < budget:
                n, v = self._recs.popleft()
                out.append(v)
                drained += n
            conn = self._conn
        # Credit OUTSIDE the lock: push_threadsafe marshals onto the hub
        # loop and must not run under the reader condition.
        if conn is not None and drained:
            try:
                conn.push_threadsafe("s_credit", sid=self.stream_id,
                                     n=drained)
            except Exception:
                pass  # producer gone: its own close path handles it
        return out

    def close(self, unlink: bool = False) -> None:
        """Unregister from the hub (signature mirrors StreamRing.close so
        proxy teardown code treats both transports alike)."""
        with self._cond:
            self._closed = True
            self._cond.notify_all()
        self._hub._readers.pop(self.stream_id, None)


class PushStreamHub:
    """Per-process stream acceptor: ONE rpc server per proxy process;
    every producer frame routes by stream id to its reader. Create with
    `await PushStreamHub.ensure(...)` from the proxy's event loop."""

    def __init__(self):
        self.host = "127.0.0.1"
        self.port = 0
        self._server = None
        self._readers: dict[str, PushStreamReader] = {}

    async def start(self, host: str = "127.0.0.1") -> int:
        from ray_tpu._private.rpc import RpcServer

        self.host = host
        self._server = RpcServer(self._on_request, on_push=self._on_push,
                                 on_close=self._on_conn_close,
                                 label=STREAM_LABEL)
        self.port = await self._server.start(host, 0)
        return self.port

    def open(self, stream_id: str, window: int) -> PushStreamReader:
        r = PushStreamReader(self, stream_id, window)
        self._readers[stream_id] = r
        return r

    def spec(self, stream_id: str, window: int) -> dict:
        """Wire form the producer connects back with (rides the stream
        handshake next to the shm ring spec)."""
        return {"host": self.host, "port": self.port,
                "stream_id": stream_id, "window": int(window)}

    async def _on_request(self, conn, method: str, a: dict):
        if method == "s_open":
            r = self._readers.get(a["sid"])
            if r is None:
                return {"ok": False}
            r._on_open(conn)
            return {"ok": True}
        if method == "s_close":
            # End-of-stream is a CALL, not a push: the reply acks that the
            # hub processed it — and, by per-connection FIFO, every s_data
            # frame before it. Without the ack the producer's socket close
            # races its own tail bytes: an unread s_credit in the
            # producer's receive buffer turns close() into an RST, and RST
            # makes the consumer's kernel DISCARD received-but-unread
            # data — the last frames of a cleanly-drained stream.
            r = self._readers.get(a.get("sid"))
            if r is not None:
                r._on_stream_close(a.get("seq"))
            return {"ok": r is not None}
        raise ValueError(f"unknown stream method {method!r}")

    async def _on_push(self, conn, method: str, a: dict):
        r = self._readers.get(a.get("sid"))
        if r is None:
            return
        if method == "s_data":
            r._on_data(a["seq"], a["recs"])

    def _on_conn_close(self, conn) -> None:
        # One producer connection per stream: a close before s_close means
        # the producer process (or the link) died mid-stream. Pushed
        # frames are dispatched as queued tasks while this callback runs
        # inline from the read loop's teardown — when s_close and EOF
        # arrive in the same segment (a graceful producer close) the
        # close callback would overtake the s_close task still sitting in
        # the ready queue, severing a cleanly-ended stream. Queue the
        # sever BEHIND those tasks; _on_close_conn is a no-op once the
        # reader saw s_close.
        def _sever():
            for r in list(self._readers.values()):
                if r._conn is conn:
                    r._on_close_conn()

        try:
            asyncio.get_running_loop().call_soon(_sever)
        except RuntimeError:
            _sever()

    async def stop(self) -> None:
        if self._server is not None:
            await self._server.stop()
            self._server = None
        for r in list(self._readers.values()):
            r._on_close_conn()
        self._readers.clear()


# --------------------------------------------------------------- producer
_IO = None
_IO_LOCK = threading.Lock()


def _io():
    """Shared per-process event-loop thread for producer connections (one
    loop carries every outbound stream, like the reference's per-process
    io_service)."""
    global _IO
    with _IO_LOCK:
        if _IO is None:
            from ray_tpu._private.rpc import EventLoopThread

            _IO = EventLoopThread(name="rt-stream-io")
        return _IO


class PushStreamWriter:
    """Producer end: StreamRing's write/close calling convention (sync,
    callable from the replica's pump threads) over an rpc connection.

    Records buffer locally and a loop-side flusher sends everything
    buffered since its last run as ONE s_data frame — a burst of writes
    while a flush is in flight coalesces into the next single frame.
    Credit accounting bounds un-acked bytes at `window`; when the buffer
    alone reaches the window the writer PARKS in write() until the
    consumer drains (or the timeout trips), so a stalled consumer can
    never make the producer buffer unboundedly.
    """

    def __init__(self, spec: dict, connect_timeout: float = 10.0):
        from ray_tpu._private import rpc as _rpc

        self.stream_id = spec["stream_id"]
        self.window = int(spec["window"])
        self._credit = self.window
        self._pending: list[bytes] = []
        self._pending_bytes = 0
        self._seq = 0
        self._inflight = 0  # s_data pushes not yet buffered on the wire
        self._severed: Optional[str] = None
        self._closed = False
        self._flush_scheduled = False
        self._cond = threading.Condition()
        io = _io()
        self._loop = io.loop
        self._conn = io.run(
            _rpc.connect(spec["host"], int(spec["port"]),
                         on_push=self._on_push, on_close=self._on_close,
                         timeout=connect_timeout, label=STREAM_LABEL),
            timeout=connect_timeout + 5)
        rep = io.run(self._conn.call("s_open", sid=self.stream_id,
                                     _timeout=connect_timeout),
                     timeout=connect_timeout + 5)
        if not (isinstance(rep, dict) and rep.get("ok")):
            io.run(self._conn.close(), timeout=5)
            raise ConnectionError(
                f"stream hub refused stream {self.stream_id!r}")

    # -- event-loop side ---------------------------------------------------
    async def _on_push(self, conn, method: str, a: dict):
        if method == "s_credit" and a.get("sid") == self.stream_id:
            with self._cond:
                self._credit += int(a["n"])
                self._cond.notify_all()
            self._flush_on_loop()

    def _on_close(self, conn) -> None:
        with self._cond:
            if self._severed is None:
                self._severed = "push-stream connection severed"
            self._cond.notify_all()

    def _flush_on_loop(self) -> None:
        """Runs on the IO loop: drain as much of the pending buffer as
        credit allows into ONE frame. Blobs ride the rpc frame's raw
        buffer lanes (no re-pickling of already-pickled records)."""
        with self._cond:
            self._flush_scheduled = False
            if (self._severed is not None or not self._pending
                    or self._credit <= 0):
                return
            take: list[bytes] = []
            taken = 0
            while self._pending and taken < self._credit:
                b = self._pending[0]
                if take and taken + len(b) > self._credit:
                    break  # next record exceeds credit: next frame
                take.append(self._pending.pop(0))
                taken += len(b)
            self._pending_bytes -= taken
            self._credit -= taken
            seq = self._seq
            self._seq += 1
            self._inflight += 1
            self._cond.notify_all()  # buffer shrank: unpark writers
        try:
            coro = self._conn.push("s_data", sid=self.stream_id, seq=seq,
                                   recs=take)
            asyncio.ensure_future(self._guard(coro))
        except Exception:
            self._guard_done()
            self._on_close(self._conn)
        _mint(len(take), taken)

    async def _guard(self, coro):
        try:
            await coro
        except Exception:
            self._on_close(self._conn)
        finally:
            self._guard_done()

    def _guard_done(self) -> None:
        with self._cond:
            self._inflight -= 1
            self._cond.notify_all()  # close() waits for inflight == 0

    def _schedule_flush(self) -> None:
        with self._cond:
            if self._flush_scheduled:
                return  # records accreting behind a scheduled flush
            self._flush_scheduled = True
        self._loop.call_soon_threadsafe(self._flush_on_loop)

    # -- pump-thread side --------------------------------------------------
    def write(self, value, timeout: float | None = None) -> None:
        """Append one record; parks while the send window is exhausted
        (consumer backpressure). Raises TimeoutError on a stalled
        consumer, ValueError on a record too large to ever fit,
        StreamSevered on a lost link, RingClosed after close()."""
        blob = pickle.dumps(value, protocol=5)
        if len(blob) > self.window // 2:
            raise ValueError(
                f"record {len(blob)}B exceeds push-stream record cap "
                f"({self.window // 2}B for a {self.window}B window)")
        deadline = None if timeout is None else time.monotonic() + timeout
        with self._cond:
            if self._closed:
                raise RingClosed("push stream is closed for writing")
            parked = False
            while self._pending_bytes + len(blob) > self.window:
                if self._severed is not None:
                    raise StreamSevered(self._severed)
                if not parked:
                    parked = True
                    _mint_park()
                left = (None if deadline is None
                        else deadline - time.monotonic())
                if left is not None and left <= 0:
                    raise TimeoutError(
                        "push stream write timed out (consumer stalled)")
                self._cond.wait(timeout=left)
            if self._severed is not None:
                raise StreamSevered(self._severed)
            self._pending.append(blob)
            self._pending_bytes += len(blob)
        self._schedule_flush()

    def close(self, unlink: bool = False) -> None:
        """Flush what remains, send end-of-stream, drop the connection.
        Sync and idempotent; signature mirrors StreamRing.close so the
        replica's teardown treats both transports alike."""
        with self._cond:
            if self._closed:
                return
            self._closed = True
        self._schedule_flush()
        # Wait until the tail frames are BUFFERED ON THE WIRE (inflight
        # counts push() coroutines not yet completed), not merely popped
        # from _pending — otherwise the s_close below could overtake the
        # final s_data frame and the consumer would drop the last burst.
        deadline = time.monotonic() + 5.0
        with self._cond:
            while ((self._pending or self._inflight)
                   and self._severed is None
                   and time.monotonic() < deadline):
                self._cond.wait(timeout=0.05)
        try:
            # End-of-stream is a CALL: the reply proves the hub processed
            # s_close and (per-connection FIFO) every data frame before
            # it, so the socket close below cannot race its own tail
            # bytes (see the hub-side comment). seq tells the consumer
            # how many frames to expect — a lost TAIL frame has no
            # successor, so the close record is the gap detector of last
            # resort.
            asyncio.run_coroutine_threadsafe(
                self._conn.call("s_close", sid=self.stream_id,
                                seq=self._seq, _timeout=5.0),
                self._loop).result(timeout=6)
        except Exception:
            pass
        try:
            asyncio.run_coroutine_threadsafe(
                self._conn.close(), self._loop).result(timeout=5)
        except Exception:
            pass
