"""@remote functions.

Parity target: reference python/ray/remote_function.py (RemoteFunction:41,
_remote:308 — options resolution, pickling the function once by value) and
the `.options(...)` override pattern.
"""

from __future__ import annotations

import functools
from typing import Any

from ray_tpu._private.resources import normalize_resources
from ray_tpu._private.task_spec import SchedulingStrategy
from ray_tpu._private.worker import global_worker


def _to_strategy(opt) -> SchedulingStrategy:
    if opt is None:
        return SchedulingStrategy()
    if isinstance(opt, SchedulingStrategy):
        return opt
    if isinstance(opt, str):
        if opt in ("DEFAULT", "SPREAD"):
            return SchedulingStrategy(kind=opt)
        raise ValueError(f"unknown scheduling strategy {opt!r}")
    # util.scheduling_strategies objects duck-type via to_internal()
    if hasattr(opt, "to_internal"):
        return opt.to_internal()
    raise TypeError(f"bad scheduling strategy {opt!r}")


_TASK_OPTION_KEYS = {
    "num_cpus", "num_tpus", "num_gpus", "resources", "memory", "num_returns",
    "max_retries", "retry_exceptions", "scheduling_strategy", "name",
    "runtime_env", "placement_group", "placement_group_bundle_index",
    # Per-attempt execution deadline, enforced worker-side: an attempt
    # running past it is interrupted and retried under max_retries as a
    # system failure (TaskTimeoutError) — README "Stall detection".
    "timeout_s",
}


class RemoteFunction:
    def __init__(self, fn, options: dict[str, Any] | None = None):
        self._fn = fn
        self._options = dict(options or {})
        # Resolved (resources, strategy) computed once on first .remote():
        # options are immutable per instance (.options() returns a new one),
        # and re-normalizing them cost ~15us per call at submit rates.
        self._resolved = None
        functools.update_wrapper(self, fn)

    def bind(self, *args, **kwargs):
        """DAG-node binding (reference dag API / workflow steps): builds a
        lazy node whose args may be other bound nodes."""
        from ray_tpu.workflow import bind as _wf_bind

        return _wf_bind(self, *args, **kwargs)

    def options(self, **overrides) -> "RemoteFunction":
        bad = set(overrides) - _TASK_OPTION_KEYS
        if bad:
            raise ValueError(f"Unknown task options: {sorted(bad)}")
        merged = dict(self._options)
        merged.update(overrides)
        return RemoteFunction(self._fn, merged)

    def remote(self, *args, **kwargs):
        w = global_worker()
        if w is None:
            raise RuntimeError("ray_tpu.init() must be called before .remote()")
        o = self._options
        if self._resolved is None:
            num_tpus = o.get("num_tpus", o.get("num_gpus"))
            resources = normalize_resources(
                num_cpus=o.get("num_cpus"),
                num_tpus=num_tpus,
                resources=o.get("resources"),
                memory=o.get("memory"),
                default_cpus=1.0,
            )
            strategy = _to_strategy(o.get("scheduling_strategy"))
            pg = o.get("placement_group")
            if pg is not None:
                strategy = SchedulingStrategy(
                    kind="PLACEMENT_GROUP",
                    pg_id=pg.id if hasattr(pg, "id") else pg,
                    pg_bundle_index=o.get("placement_group_bundle_index", -1),
                )
            self._resolved = (resources, strategy)
        resources, strategy = self._resolved
        num_returns = o.get("num_returns", 1)
        refs = w.submit_task(
            self._fn,
            args,
            kwargs,
            name=o.get("name"),
            num_returns=num_returns,
            resources=resources,
            strategy=strategy,
            max_retries=o.get("max_retries"),
            retry_exceptions=o.get("retry_exceptions", False),
            runtime_env=o.get("runtime_env"),
            timeout_s=o.get("timeout_s"),
        )
        if num_returns == 1:
            return refs[0]
        return refs

    def __call__(self, *args, **kwargs):
        raise TypeError(
            f"Remote function {self._fn.__name__!r} cannot be called directly; "
            f"use {self._fn.__name__}.remote()."
        )
