"""`ray-tpu` command line: start/stop/status for multi-machine clusters.

Parity target: reference python/ray/scripts/scripts.py:706 (`ray start
--head` / `--address`, `ray stop`, `ray status`). The head runs as a
detached process (controller + local node agent); joining nodes spawn a
detached NodeAgent pointed at the head. State lives under --session-dir
(default /tmp/ray_tpu_<uid>).
"""

from __future__ import annotations

import argparse
import json
import os
import signal
import subprocess
import sys
import time


def _default_session_dir() -> str:
    return os.path.join("/tmp", f"ray_tpu_{os.getuid()}")


class _Client:
    """One loop + one registered connection, reused across CLI calls (the
    join path polls the controller; per-call thread/socket churn would fire
    the controller's client-reap machinery hundreds of times)."""

    def __init__(self, address: str):
        from ray_tpu._private import rpc

        self._rpc = rpc
        self.host, port = address.rsplit(":", 1)
        self.port = int(port)
        self.loop = rpc.EventLoopThread(name="ray-tpu-cli")
        self._conn = None

    def call(self, method: str, timeout: float = 10.0, **kw):
        async def _go():
            if self._conn is None or self._conn.closed:
                self._conn = await self._rpc.connect(
                    self.host, self.port, timeout=timeout)
                await self._conn.call("register", kind="client",
                                      worker_id="ray-tpu-cli", address=None)
            return await self._conn.call(method, **kw)

        return self.loop.run(_go(), timeout=timeout + 5)

    def close(self):
        if self._conn is not None:
            conn, self._conn = self._conn, None

            async def _bye():
                await conn.close()

            try:
                self.loop.run(_bye(), timeout=5)
            except Exception:
                pass
        self.loop.stop()


def _rpc_call(address: str, method: str, timeout: float = 10.0, **kw):
    c = _Client(address)
    try:
        return c.call(method, timeout=timeout, **kw)
    finally:
        c.close()


def _wait_for(pred, timeout: float, what: str, proc=None, log_file=None):
    """Poll pred; fail FAST (with the child's log tail) if proc died."""
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if proc is not None and proc.poll() is not None:
            raise RuntimeError(
                f"{what}: process exited with code {proc.returncode}"
                + _log_tail(log_file))
        try:
            out = pred()
            if out:
                return out
        except Exception:
            pass
        time.sleep(0.2)
    raise TimeoutError(f"timed out waiting for {what}" + _log_tail(log_file))


def _log_tail(log_file) -> str:
    if not log_file or not os.path.exists(log_file):
        return ""
    try:
        with open(log_file) as f:
            tail = f.read()[-2000:]
        return f"\n--- {log_file} ---\n{tail}" if tail.strip() else ""
    except OSError:
        return ""


def _spawn_logged(cmd, session_dir: str, name: str):
    log_path = os.path.join(session_dir, f"{name}.log")
    log = open(log_path, "ab")
    proc = subprocess.Popen(cmd, start_new_session=True,
                            stdout=log, stderr=subprocess.STDOUT)
    log.close()
    return proc, log_path


def cmd_start(args) -> int:
    os.makedirs(args.session_dir, exist_ok=True)
    if args.head:
        head_file = os.path.join(args.session_dir, "head.json")
        if os.path.exists(head_file):
            old = json.load(open(head_file))
            if _is_ours(old.get("pid", -1)):
                print(f"head already running (pid {old['pid']}); "
                      f"run `ray-tpu stop` first", file=sys.stderr)
                return 1
            os.unlink(head_file)  # stale file from a crashed head
        cmd = [sys.executable, "-m", "ray_tpu.scripts.head_main",
               "--host", args.host, "--port", str(args.port),
               "--session-dir", args.session_dir,
               "--resources", args.resources]
        if args.num_cpus is not None:
            cmd += ["--num-cpus", str(args.num_cpus)]
        if args.num_tpus is not None:
            cmd += ["--num-tpus", str(args.num_tpus)]
        proc, log_path = _spawn_logged(cmd, args.session_dir, "head")
        info = _wait_for(lambda: (json.load(open(head_file))
                                  if os.path.exists(head_file) else None),
                         30, "head startup", proc=proc, log_file=log_path)
        _wait_for(lambda: _rpc_call(info["address"], "cluster_info"),
                  30, "controller", proc=proc, log_file=log_path)
        print(f"ray-tpu head started at {info['address']} (pid {proc.pid})")
        print(f"join other machines with: ray-tpu start --address {info['address']}")
        return 0

    if not args.address:
        print("pass --head or --address host:port", file=sys.stderr)
        return 1
    info = _rpc_call(args.address, "cluster_info")
    from ray_tpu._private.ids import NodeID
    from ray_tpu._private.accelerators import host_resources
    from ray_tpu._private.resources import ResourceSet

    res = host_resources(args.num_cpus, args.num_tpus)
    res.update(json.loads(args.resources))
    node_id = NodeID.from_random().hex()
    cmd = [sys.executable, "-m", "ray_tpu._private.node_agent",
           "--controller", args.address,
           "--node-id", node_id,
           "--session", info["session"],
           "--resources", json.dumps(ResourceSet(res).raw()),
           "--labels", "{}"]
    proc, log_path = _spawn_logged(cmd, args.session_dir,
                                   f"node-{node_id[:8]}")
    nodes_file = os.path.join(args.session_dir, "nodes.json")
    nodes = []
    if os.path.exists(nodes_file):
        nodes = json.load(open(nodes_file))
    nodes.append({"node_id": node_id, "pid": proc.pid})
    with open(nodes_file, "w") as f:
        json.dump(nodes, f)

    client = _Client(args.address)
    try:
        def _alive():
            snap = client.call("state_snapshot")
            ent = snap["nodes"].get(node_id)
            return ent is not None and ent["alive"]

        _wait_for(_alive, 60, "node registration", proc=proc,
                  log_file=log_path)
    finally:
        client.close()
    print(f"node {node_id[:8]} joined {args.address} (pid {proc.pid})")
    return 0


def cmd_stop(args) -> int:
    stopped = 0
    nodes_file = os.path.join(args.session_dir, "nodes.json")
    if os.path.exists(nodes_file):
        for ent in json.load(open(nodes_file)):
            stopped += _kill(ent["pid"])
        os.unlink(nodes_file)
    head_file = os.path.join(args.session_dir, "head.json")
    if os.path.exists(head_file):
        stopped += _kill(json.load(open(head_file))["pid"])
        os.unlink(head_file)
    print(f"stopped {stopped} process(es)")
    return 0


def _is_ours(pid: int) -> bool:
    """Never kill a recycled PID: the process must actually be a ray-tpu
    head/agent (reference `ray stop` matches cmdlines the same way)."""
    try:
        with open(f"/proc/{pid}/cmdline", "rb") as f:
            cmdline = f.read().replace(b"\x00", b" ")
    except OSError:
        return False
    return (b"ray_tpu.scripts.head_main" in cmdline
            or b"ray_tpu._private.node_agent" in cmdline)


def _kill(pid: int) -> int:
    if not _is_ours(pid):
        return 0
    try:
        os.kill(pid, signal.SIGTERM)
    except ProcessLookupError:
        return 0
    for _ in range(50):
        try:
            os.kill(pid, 0)
        except ProcessLookupError:
            return 1
        time.sleep(0.1)
    try:
        os.kill(pid, signal.SIGKILL)
    except ProcessLookupError:
        pass
    return 1


def cmd_status(args) -> int:
    address = args.address
    if not address:
        head_file = os.path.join(args.session_dir, "head.json")
        if not os.path.exists(head_file):
            print("no head recorded; pass --address", file=sys.stderr)
            return 1
        address = json.load(open(head_file))["address"]
    snap = _rpc_call(address, "state_snapshot")
    info = _rpc_call(address, "cluster_info")
    print(f"cluster {address} (session {info['session'][:8]})")
    for nid, n in snap["nodes"].items():
        state = n.get("liveness") or ("ALIVE" if n["alive"] else "DEAD")
        print(f"  node {nid[:8]} {state} total={n['total']} available={n['available']}")
    actors = snap.get("actors", {})
    alive_actors = sum(1 for a in actors.values() if a.get("state") != "DEAD")
    print(f"  actors: {alive_actors}  pending tasks: {snap.get('pending_tasks', 0)}")
    return 0


def _resolve_address(args) -> str:
    if getattr(args, "address", None):
        return args.address
    env = os.environ.get("RT_ADDRESS")
    if env:
        return env
    head_file = os.path.join(args.session_dir, "head.json")
    if os.path.exists(head_file):
        return json.load(open(head_file))["address"]
    raise SystemExit("no head recorded; pass --address or set RT_ADDRESS")


def cmd_job(args) -> int:
    """`ray-tpu job submit|status|logs|stop|list` (reference `ray job ...`,
    dashboard/modules/job/cli.py)."""
    from ray_tpu.job_submission import JobStatus, JobSubmissionClient

    client = JobSubmissionClient(_resolve_address(args))
    try:
        if args.job_cmd == "submit":
            import shlex

            ep = args.entrypoint
            if ep and ep[0] == "--":
                ep = ep[1:]
            # Re-quote: the entrypoint runs under `sh -c` on the job node.
            sid = client.submit_job(entrypoint=shlex.join(ep),
                                    submission_id=args.submission_id)
            print(f"submitted: {sid}")
            if args.no_wait:
                return 0
            for chunk in client.tail_job_logs(sid):
                print(chunk, end="")
            status = client.get_job_status(sid)
            print(f"job {sid}: {status}")
            return 0 if status == JobStatus.SUCCEEDED else 1
        if args.job_cmd == "status":
            print(client.get_job_status(args.submission_id))
            return 0
        if args.job_cmd == "logs":
            print(client.get_job_logs(args.submission_id), end="")
            return 0
        if args.job_cmd == "stop":
            stopped = client.stop_job(args.submission_id)
            print("stopped" if stopped else "not running")
            return 0
        if args.job_cmd == "list":
            for j in client.list_jobs():
                print(f"{j['submission_id']}  {j['status']:<9}  {j['entrypoint']}")
            return 0
        raise SystemExit(f"unknown job command {args.job_cmd}")
    finally:
        client.close()


def cmd_checkpoints(args) -> int:
    """`ray-tpu checkpoints` — checkpoint observability (README
    "Checkpointing & storage"). With --path, scans a storage URI directly
    (committed + in-flight partial rows, no cluster needed); otherwise
    lists the cluster-wide registry every engine commit registers in the
    controller KV."""
    rows: list[dict]
    if args.path:
        from ray_tpu.train import checkpoint as ckpt_mod

        rows = ckpt_mod.list_checkpoints(args.path)
    else:
        address = _resolve_address(args)
        keys = _rpc_call(address, "kv_keys", ns="_checkpoints",
                         prefix="")["keys"]
        rows = []
        for key in sorted(keys):
            val = _rpc_call(address, "kv_get", ns="_checkpoints",
                            key=key)["value"]
            if val is None:
                continue
            try:
                rows.append(json.loads(val))
            except ValueError:
                pass
        rows.sort(key=lambda r: r.get("created") or 0)
    if not rows:
        print("no checkpoints")
        return 0
    print(f"{'STEP':>6}  {'KIND':<9} {'BYTES':>12}  {'STATE':<9} URI")
    for r in rows:
        committed = r.get("committed", True)
        state = "committed" if committed else "partial"
        if r.get("pins"):
            state += f"+{len(r['pins'])}pin"
        step = r.get("step")
        print(f"{step if step is not None else '-':>6}  "
              f"{(r.get('kind') or '-'):<9} "
              f"{(r.get('bytes') if r.get('bytes') is not None else '-'):>12}  "
              f"{state:<9} {r.get('uri') or r.get('name')}")
    return 0


def cmd_stalls(args) -> int:
    """`ray-tpu stalls` — stall-detection observability (README "Stall
    detection & watchdogs"). Lists the StallReports the controller has
    aggregated: every warn/dump/kill escalation from worker watchdogs,
    every agent backstop (progress beacons stopped), and every train
    group-stall kill. Use --verbose for the flight-recorder tail and the
    storage path of the persisted flight dump."""
    rows = _rpc_call(_resolve_address(args), "list_stalls",
                     limit=args.limit)["stalls"]
    if not rows:
        print("no stalls recorded (escalation ladder idle — arm it with "
              "RT_STALL_WARN_S / RT_STALL_DUMP_S / RT_STALL_KILL_S)")
        return 0
    print(f"{'STAGE':<6} {'SCOPE':<12} {'TASK':<24} {'SILENT':>8}  "
          f"{'NODE':<10} {'PID':>7}  REASON")
    for r in rows:
        name = (r.get("name") or r.get("task_id") or "-")
        print(f"{(r.get('stage') or '-'):<6} "
              f"{(r.get('scope') or '-'):<12} "
              f"{str(name)[:24]:<24} "
              f"{(r.get('silence_s') if r.get('silence_s') is not None else '-'):>8}  "
              f"{str(r.get('node_id') or '-')[:10]:<10} "
              f"{(r.get('pid') or '-'):>7}  "
              f"{(r.get('reason') or '')[:60]}")
        if r.get("trace_id"):
            print(f"       trace: {r['trace_id']}  "
                  f"(ray-tpu timeline --trace {r['trace_id'][:12]})")
        if args.verbose:
            if r.get("flight_path"):
                print(f"       flight dump: {r['flight_path']}")
            for ev in r.get("events") or []:
                print(f"       {ev}")
    return 0


def _print_event_rows(rows: list, verbose: bool) -> None:
    for r in rows:
        ent = ",".join(str(e)[:12] for e in (r.get("entity") or [])) or "-"
        ts = time.strftime("%H:%M:%S", time.localtime(r.get("ts") or 0))
        print(f"{r.get('seq', '-'):>7} {ts} "
              f"{(r.get('sev') or '-'):<8} "
              f"{(r.get('kind') or '-'):<20} "
              f"{str(r.get('node') or '-')[:10]:<10} "
              f"{ent:<26} "
              f"{(r.get('msg') or '')[:70]}")
        if r.get("trace_id"):
            print(f"        trace: {r['trace_id']}  "
                  f"(ray-tpu timeline --trace {str(r['trace_id'])[:12]})")
        if verbose and r.get("attrs"):
            print(f"        {r['attrs']}")


def cmd_events(args) -> int:
    """`ray-tpu events` — the cluster event plane (README "Cluster
    events"): durable lifecycle history. Lists events newest-last; filter
    with --entity (prefix-matches actor/worker/task/lease/node/job ids),
    --kind, --severity; --follow polls for new seqs (the controller reply's
    next_seq cursor). Stall events print their trace link so
    `ray-tpu events` -> `ray-tpu timeline --trace` chains."""
    kw: dict = {"limit": args.limit}
    if args.entity:
        kw["entity"] = args.entity
    if args.kind:
        kw["kind"] = args.kind
    if args.severity:
        kw["severity"] = args.severity
    header = (f"{'SEQ':>7} {'TIME':<8} {'SEV':<8} {'KIND':<20} "
              f"{'NODE':<10} {'ENTITY':<26} MESSAGE")
    if not args.follow:
        rep = _rpc_call(_resolve_address(args), "list_events", **kw)
        rows = rep["events"]
        if not rows:
            print("no events recorded (plane disabled? arm with "
                  "RT_EVENTS_BUFFER > 0 — the default)")
            return 0
        print(header)
        _print_event_rows(rows, args.verbose)
        if rep.get("truncated"):
            print(f"(truncated to the newest {args.limit}; raise --limit)")
        return 0
    client = _Client(_resolve_address(args))
    since = None
    try:
        print(header)
        while True:
            rep = client.call("list_events",
                              **({**kw, "since": since} if since is not None
                                 else kw))
            _print_event_rows(rep["events"], args.verbose)
            if rep.get("truncated"):
                # Never a silently short answer: a burst bigger than
                # --limit between polls drops its oldest rows — say so.
                print(f"(burst exceeded --limit {args.limit}; oldest "
                      f"rows of this poll were dropped)")
            # next_seq is the next seq the controller will MINT; the last
            # seen seq is one below it (since= is exclusive).
            nxt = rep.get("next_seq")
            if nxt is not None:
                since = nxt - 1
            time.sleep(args.interval)
    except KeyboardInterrupt:
        return 0
    finally:
        client.close()


def _fmt_bytes(n) -> str:
    if n is None:
        return "-"
    n = float(n)
    for unit in ("B", "K", "M", "G", "T"):
        if n < 1024 or unit == "T":
            return f"{n:.0f}{unit}" if unit != "B" else f"{int(n)}B"
        n /= 1024
    return "-"


def _top_lines(rep: dict) -> list[str]:
    """Render one `ray-tpu top` frame from a cluster_utilization reply:
    one row per node (per-worker device series aggregated up), DEAD nodes
    marked rather than freezing their last values."""
    lines = [f"{'NODE':<10} {'STATE':<8} {'CPU%':>6} {'MEM%':>6} "
             f"{'RSS':>8} {'HBM USED/PEAK':>16} {'COMPILE_S':>10} "
             f"{'TOK/S':>8} {'PP%':>5} {'DATA IF/SPILL':>14} "
             f"{'TASKS':>6}  WORKERS"]
    nodes = rep.get("nodes") or {}
    for nid in sorted(nodes):
        n = nodes[nid]
        dead = not n.get("alive")
        state = (n.get("liveness") or ("ALIVE" if not dead else "DEAD"))
        nd = n.get("node") or {}
        workers = n.get("workers") or {}
        # distinguish "no worker reports HBM" from a genuine 0 in-use
        # (freed arrays must still show their peak)
        have_hbm = any("hbm_used" in w for w in workers.values())
        hbm_used = sum(w.get("hbm_used", 0)
                       for w in workers.values()) if have_hbm else None
        hbm_peak = sum(w.get("hbm_peak", 0)
                       for w in workers.values()) if have_hbm else None
        compile_s = sum(w.get("compile_s", 0.0) for w in workers.values())
        # Live decode throughput (README "Serving hot loop"): summed over
        # the node's engine-hosting workers; "-" when none serve.
        have_tok = any("llm.tokens_per_s" in w for w in workers.values())
        tok_s = sum(w.get("llm.tokens_per_s", 0.0)
                    for w in workers.values()) if have_tok else None
        # Pipeline-stage occupancy (README "Pipeline-parallel serving"):
        # the node's WORST stage busy fraction — the bubble shows as a low
        # PP% on the stage everyone else waits for; "-" when no stage here.
        pp_vals = [w["llm.pp_occupancy"] for w in workers.values()
                   if "llm.pp_occupancy" in w]
        pp_occ = min(pp_vals) if pp_vals else None
        # Data-plane exchange pressure (README "Data plane"): blocks in
        # flight + spilled bytes summed over the node's exchange-driving
        # workers; "-" when no exchange ran here.
        have_data = any("data.blocks_inflight" in w
                        for w in workers.values())
        data_if = sum(w.get("data.blocks_inflight", 0)
                      for w in workers.values()) if have_data else None
        data_spill = sum(w.get("data.spilled_bytes", 0)
                         for w in workers.values()) if have_data else None
        if dead:
            # A not-alive node's stale values must not render as live
            # readings; keep the real liveness (SUSPECT nodes are frozen
            # pending rejoin, not lost).
            lines.append(f"{nid[:8]:<10} {state or 'DEAD':<8} {'-':>6} "
                         f"{'-':>6} {'-':>8} {'-':>16} {'-':>10} {'-':>8} "
                         f"{'-':>5} {'-':>14} {'-':>6}")
            continue
        hbm = (f"{_fmt_bytes(hbm_used)}/{_fmt_bytes(hbm_peak)}"
               if hbm_used is not None else "-")
        cpu = nd.get("cpu")
        mem = nd.get("mem")
        lines.append(
            f"{nid[:8]:<10} {state:<8} "
            f"{cpu if cpu is not None else '-':>6} "
            f"{mem if mem is not None else '-':>6} "
            f"{_fmt_bytes(nd.get('rss')):>8} {hbm:>16} "
            f"{compile_s:>10.2f} "
            f"{(f'{tok_s:.0f}' if tok_s is not None else '-'):>8} "
            f"{(f'{pp_occ * 100:.0f}' if pp_occ is not None else '-'):>5} "
            f"{(f'{data_if}/{_fmt_bytes(data_spill)}' if data_if is not None else '-'):>14} "
            f"{int(nd.get('tasks_running', 0)):>6}  {len(workers)}")
    ctrl = rep.get("controller") or {}
    tables = ctrl.get("tables") or {}
    lag = ctrl.get("loop_lag_s")
    lines.append(
        f"controller: loop_lag={lag if lag is not None else '-'}s  "
        f"objects={tables.get('objects', 0)} actors={tables.get('actors', 0)} "
        f"leases={tables.get('leases', 0)} "
        f"parked={tables.get('parked_grants', 0)} "
        f"rpcs={ctrl.get('rpc_total', 0)}")
    # Ingress fleet + push-stream transport (README "Cross-host streaming
    # & multi-proxy"): one row when any proxy has reported metrics.
    serve = rep.get("serve") or {}
    proxies = serve.get("proxies") or {}
    if proxies:
        frag = "  ".join(
            f"{pid}: req={row.get('requests', 0)} "
            f"sse={row.get('streams', 0)} active={row.get('active', 0)}"
            for pid, row in sorted(proxies.items()))
        stream = serve.get("stream") or {}
        lines.append(
            f"serve: {frag}  push-stream: "
            f"recs={stream.get('records', 0)} "
            f"bytes={_fmt_bytes(stream.get('bytes', 0))} "
            f"parks={stream.get('parks', 0)}")
    if not rep.get("telemetry_armed"):
        lines.append("(telemetry idle — start the cluster with "
                     "RT_TELEMETRY_INTERVAL_S=1 for live samples)")
    return lines


def cmd_top(args) -> int:
    """`ray-tpu top` — live cluster utilization (README "Telemetry &
    profiling"): one redraw-in-place row per node with cpu/mem/rss/hbm/
    compile/tasks columns fed by the telemetry plane
    (RT_TELEMETRY_INTERVAL_S), plus the controller's self-stats line.
    Curses-free: plain ANSI cursor-up redraw; --once prints one frame."""
    client = _Client(_resolve_address(args))
    prev_lines = 0
    try:
        while True:
            try:
                rep = client.call("cluster_utilization")
            except Exception as e:
                # A transient controller blip (restart, timeout under
                # load) must not crash a long-running monitor — _Client
                # reconnects on the next call.
                if args.once:
                    raise
                lines = [f"controller unreachable "
                         f"({type(e).__name__}: {e}) — retrying"]
            else:
                lines = _top_lines(rep)
            if prev_lines:
                # redraw in place: cursor up + clear to end of screen
                sys.stdout.write(f"\x1b[{prev_lines}F\x1b[J")
            print("\n".join(lines), flush=True)
            if args.once:
                return 0
            prev_lines = len(lines)
            time.sleep(args.interval)
    except KeyboardInterrupt:
        return 0
    finally:
        client.close()


def cmd_profile(args) -> int:
    """`ray-tpu profile --worker ID` — on-demand capture of a live worker
    (README "Telemetry & profiling"). cpu: in-process sampling profiler
    over the worker's threads, rendered as collapsed stacks + Chrome-trace
    flame events; jax: a jax.profiler trace window zipped from the worker.
    Captures persist through the storage plane under <session>/profiles/
    and are listed by `/api/profiles` / `util.state.list_profiles()`."""
    address = _resolve_address(args)
    rep = _rpc_call(address, "profile_worker", timeout=args.seconds + 60,
                    worker_id=args.worker, seconds=args.seconds,
                    mode=args.mode)
    if not rep.get("found"):
        print(f"profile failed: {rep.get('error')}", file=sys.stderr)
        return 1
    meta = rep["profile"]
    print(f"profiled worker {meta.get('worker_id', '')[:12]} "
          f"({meta['mode']}, {meta.get('seconds')}s, "
          f"{meta.get('samples', meta.get('files', 0))} samples)")
    print(f"  persisted: {meta['path']}")
    if meta.get("archive_path"):
        print(f"  trace archive: {meta['archive_path']}")
    if args.output and args.mode != "cpu":
        print(f"-o applies to cpu mode only (jax captures persist as the "
              f"trace archive above); {args.output} not written",
              file=sys.stderr)
    if args.mode == "cpu":
        doc = _rpc_call(address, "get_profile", name=meta["name"],
                        timeout=60)
        if not doc.get("found"):
            # The capture DID persist (path above); only the readback
            # failed — say so instead of writing an empty trace as
            # success.
            print(f"profile persisted but fetch failed: "
                  f"{doc.get('error')}", file=sys.stderr)
            return 1
        collapsed = doc.get("collapsed") or {}
        if args.output:
            with open(args.output, "w") as f:
                json.dump({"traceEvents": doc.get("traceEvents") or [],
                           "displayTimeUnit": "ms"}, f)
            print(f"  wrote Chrome-trace JSON to {args.output} — open in "
                  f"https://ui.perfetto.dev")
        top = sorted(collapsed.items(), key=lambda kv: -kv[1])[:5]
        if top:
            print("  hottest stacks:")
            for stack, count in top:
                leaf = stack.rsplit(";", 1)[-1]
                print(f"    {count:>5}  {leaf}")
    return 0


def _chrome_trace_events(spans: list) -> list[dict]:
    """Convert controller span dicts to Chrome-trace/Perfetto events:
    complete "X" events laned by (worker process, thread), plus "M"
    process-name metadata. Returned unsorted; the caller sorts by ts (the
    catapult importer wants monotonic timestamps)."""
    events: list[dict] = []
    pids: dict[str, int] = {}
    for sp in spans:
        w = str(sp.get("w") or "?")
        pid = pids.get(w)
        if pid is None:
            pid = pids[w] = len(pids) + 1
            events.append({"ph": "M", "name": "process_name", "pid": pid,
                           "args": {"name": f"worker {w} "
                                            f"(os pid {sp.get('pid', '?')})"}})
        start = float(sp.get("a") or 0.0)
        end = float(sp.get("b") or start)
        args = {"trace_id": sp.get("t"), "span_id": sp.get("s"),
                "parent": sp.get("p")}
        args.update(sp.get("at") or {})
        events.append({
            "ph": "X",
            "name": str(sp.get("n") or "?"),
            "cat": str(sp.get("k") or "span"),
            "pid": pid,
            "tid": int(sp.get("tid") or 0),
            "ts": start * 1e6,
            "dur": max(1.0, (end - start) * 1e6),
            "args": args,
        })
    return events


def cmd_timeline(args) -> int:
    """`ray-tpu timeline` — export traced request/task timelines (README
    "Tracing & timeline") as Chrome-trace-event JSON that loads directly in
    Perfetto (ui.perfetto.dev) or chrome://tracing. Selects one trace
    (--trace ID, unique prefixes ok) or the N most recent (--last, default
    all indexed); requires the cluster to run with RT_TRACING=1."""
    address = _resolve_address(args)
    if args.trace:
        ids = [args.trace]
    else:
        rows = _rpc_call(address, "list_traces", limit=100_000)["traces"]
        rows.sort(key=lambda r: r.get("start") or 0)
        if args.last is not None:
            rows = rows[-args.last:]
        ids = [r["trace_id"] for r in rows]
    if not ids:
        print("no traces indexed (is the cluster running with RT_TRACING=1 "
              "and has a sampled request completed?)", file=sys.stderr)
        return 1
    events: list[dict] = []
    missing = 0
    for tid in ids:
        rep = _rpc_call(address, "get_trace", trace_id=tid)
        if not rep.get("found"):
            missing += 1
            continue
        events.extend(_chrome_trace_events(rep["spans"]))
    if missing:
        print(f"warning: {missing} trace(s) not found (evicted and not "
              f"persisted?)", file=sys.stderr)
    if not events:
        print("no spans found for the selected trace(s)", file=sys.stderr)
        return 1
    events.sort(key=lambda e: e.get("ts", 0.0))
    doc = {"traceEvents": events, "displayTimeUnit": "ms"}
    if args.output:
        with open(args.output, "w") as f:
            json.dump(doc, f)
        nspans = sum(1 for e in events if e["ph"] == "X")
        print(f"wrote {nspans} span(s) from {len(ids) - missing} trace(s) "
              f"to {args.output} — open in https://ui.perfetto.dev")
    else:
        print(json.dumps(doc))
    return 0


def cmd_lint(args) -> int:
    """`ray-tpu lint` — the rtcheck static analysis suite (README "Static
    analysis & invariants"): five AST passes encoding the runtime's
    invariants (async-blocking, wire-schema, knob-registry,
    lock-discipline, exception-taxonomy). Exit 0 = no non-baselined
    findings."""
    try:
        from tools.rtcheck import core as rtcheck_core
    except ImportError:
        # Installed entry point outside the repo (or a foreign top-level
        # `tools` package shadowing ours): resolve tools/ relative to the
        # ray_tpu package's checkout and retry with the stale module
        # purged — sys.modules would otherwise pin the foreign package.
        import ray_tpu

        repo = os.path.dirname(os.path.dirname(
            os.path.abspath(ray_tpu.__file__)))
        if not os.path.isdir(os.path.join(repo, "tools", "rtcheck")):
            print("ray-tpu lint needs the tools/rtcheck checkout "
                  "(run from the repo)", file=sys.stderr)
            return 2
        for mod in [m for m in sys.modules
                    if m == "tools" or m.startswith("tools.")]:
            del sys.modules[mod]
        sys.path.insert(0, repo)
        try:
            from tools.rtcheck import core as rtcheck_core
        except ImportError as e:
            print(f"ray-tpu lint could not import tools/rtcheck from "
                  f"{repo}: {e}", file=sys.stderr)
            return 2
    argv = list(args.paths)
    if args.json:
        argv.append("--json")
    if args.no_cache:
        argv.append("--no-cache")
    return rtcheck_core.main(argv)


def cmd_dashboard(args) -> int:
    from ray_tpu.dashboard import Dashboard

    d = Dashboard(_resolve_address(args), host=args.host, port=args.port)
    port = d.start()
    print(f"dashboard at http://{args.host}:{port}")
    try:
        while True:
            time.sleep(3600)
    except KeyboardInterrupt:
        d.stop()
    return 0


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="ray-tpu")
    p.add_argument("--session-dir", default=_default_session_dir())
    sub = p.add_subparsers(dest="cmd", required=True)

    ps = sub.add_parser("start", help="start a head or join a cluster")
    ps.add_argument("--head", action="store_true")
    ps.add_argument("--address", default=None, help="head host:port to join")
    ps.add_argument("--host", default="127.0.0.1")
    ps.add_argument("--port", type=int, default=6380)
    ps.add_argument("--num-cpus", type=float, default=None)
    ps.add_argument("--num-tpus", type=float, default=None)
    ps.add_argument("--resources", default="{}")
    ps.set_defaults(fn=cmd_start)

    pq = sub.add_parser("stop", help="stop processes started on this machine")
    pq.set_defaults(fn=cmd_stop)

    pt = sub.add_parser("status", help="print cluster state")
    pt.add_argument("--address", default=None)
    pt.set_defaults(fn=cmd_status)

    pj = sub.add_parser("job", help="submit and manage jobs")
    pj.add_argument("--address", default=None)
    jsub = pj.add_subparsers(dest="job_cmd", required=True)
    js = jsub.add_parser("submit")
    js.add_argument("--submission-id", default=None)
    js.add_argument("--no-wait", action="store_true")
    js.add_argument("entrypoint", nargs=argparse.REMAINDER,
                    help="shell command, e.g. -- python train.py")
    for name in ("status", "logs", "stop"):
        jp = jsub.add_parser(name)
        jp.add_argument("submission_id")
    jsub.add_parser("list")
    pj.set_defaults(fn=cmd_job)

    pc = sub.add_parser("checkpoints",
                        help="list checkpoints (cluster registry or a "
                             "storage URI)")
    pc.add_argument("--address", default=None)
    pc.add_argument("--path", default=None,
                    help="storage URI to scan directly (local://, sim://, "
                         "a bare path)")
    pc.set_defaults(fn=cmd_checkpoints)

    pl = sub.add_parser(
        "stalls",
        help="list stall escalations (warn/dump/kill StallReports)",
        description="List the StallReports the controller has aggregated: "
                    "worker-watchdog escalations (a task past RT_STALL_WARN_S"
                    "/RT_STALL_DUMP_S/RT_STALL_KILL_S of progress silence), "
                    "node-agent backstops (progress beacons stopped), and "
                    "train group-stall kills. dump/kill rows carry live "
                    "thread stacks and the storage URI of the persisted "
                    "flight dump.")
    pl.add_argument("--address", default=None)
    pl.add_argument("--limit", type=int, default=1000)
    pl.add_argument("--verbose", action="store_true",
                    help="show flight-recorder tails and dump paths")
    pl.set_defaults(fn=cmd_stalls)

    pe = sub.add_parser(
        "events",
        help="list cluster lifecycle events (the durable event plane)",
        description="List the cluster event plane's lifecycle history: "
                    "node register/SUSPECT/dead, worker start/exit with "
                    "normalized cause, actor create/restart/death, lease "
                    "failover + dedup replay, device-object producer loss, "
                    "checkpoint commit/GC, train group restarts, serve "
                    "deploy/scale/replica death, job start/stop, and every "
                    "stall-escalation stage (with its trace link). Events "
                    "persist under <session>/events/ as segmented JSONL "
                    "and survive controller restarts.")
    pe.add_argument("--address", default=None)
    pe.add_argument("--entity", default=None,
                    help="filter: prefix-match any entity id (actor/worker/"
                         "task/lease/node/job)")
    pe.add_argument("--kind", default=None,
                    help="filter: one event kind (see the README kind table)")
    pe.add_argument("--severity", default=None,
                    choices=("debug", "info", "warning", "error"))
    pe.add_argument("--limit", type=int, default=1000)
    pe.add_argument("--follow", action="store_true",
                    help="poll for new events (seq cursor) until ^C")
    pe.add_argument("--interval", type=float, default=1.0,
                    help="--follow poll period seconds (default 1)")
    pe.add_argument("--verbose", action="store_true",
                    help="also print each event's attrs dict")
    pe.set_defaults(fn=cmd_events)

    pm = sub.add_parser(
        "timeline",
        help="export traced timelines as Perfetto/Chrome-trace JSON",
        description="Export the distributed-tracing plane's causal spans "
                    "(submit -> dispatch -> execute -> RPC/collective/"
                    "storage ops -> engine decode iterations) as Chrome-"
                    "trace-event JSON. Load the output in "
                    "https://ui.perfetto.dev or chrome://tracing. Requires "
                    "a cluster running with RT_TRACING=1; sample with "
                    "RT_TRACE_SAMPLE.")
    pm.add_argument("--address", default=None)
    pm.add_argument("--trace", default=None,
                    help="one trace id (unique prefixes accepted)")
    pm.add_argument("--last", type=int, default=None,
                    help="export only the N most recent traces")
    pm.add_argument("-o", "--output", default=None,
                    help="write JSON here (default: stdout)")
    pm.set_defaults(fn=cmd_timeline)

    pn = sub.add_parser(
        "lint",
        help="run the rtcheck static analysis suite",
        description="Run tools/rtcheck: the five invariant passes "
                    "(async-blocking, wire-schema, knob-registry, "
                    "lock-discipline, exception-taxonomy) over ray_tpu/ + "
                    "tools/. Suppress deliberate findings inline with "
                    "`# rtcheck: disable=<pass>`; grandfathered findings "
                    "live in tools/rtcheck/baseline.json.")
    pn.add_argument("paths", nargs="*", default=[],
                    help="roots to analyze (default: ray_tpu tools)")
    pn.add_argument("--json", action="store_true",
                    help="machine-readable findings for tooling")
    pn.add_argument("--no-cache", action="store_true")
    pn.set_defaults(fn=cmd_lint)

    po = sub.add_parser(
        "top",
        help="live per-node utilization (cpu/mem/rss/hbm/compile/tasks)",
        description="Redraw-in-place cluster utilization from the "
                    "telemetry plane: per-node CPU/mem/RSS, aggregated "
                    "worker HBM use, cumulative jax compile seconds, and "
                    "running-task counts, plus the controller's self-stats "
                    "(event-loop lag, table sizes). Arm sampling with "
                    "RT_TELEMETRY_INTERVAL_S on the cluster.")
    po.add_argument("--address", default=None)
    po.add_argument("--interval", type=float, default=2.0,
                    help="refresh period seconds (default 2)")
    po.add_argument("--once", action="store_true",
                    help="print one frame and exit (no escape codes)")
    po.set_defaults(fn=cmd_top)

    pp = sub.add_parser(
        "profile",
        help="capture an on-demand profile of a live worker",
        description="Ask the worker's node agent for a live capture: "
                    "--mode cpu samples every thread's stack at "
                    "RT_PROFILE_HZ for the window (collapsed stacks + "
                    "Chrome-trace flame events); --mode jax records a "
                    "jax.profiler trace window. Captures persist through "
                    "the storage plane under <session>/profiles/ and are "
                    "listed by /api/profiles and "
                    "util.state.list_profiles().")
    pp.add_argument("--address", default=None)
    pp.add_argument("--worker", required=True,
                    help="worker id (unique prefixes accepted)")
    pp.add_argument("--seconds", type=float, default=5.0)
    pp.add_argument("--mode", choices=("cpu", "jax"), default="cpu")
    pp.add_argument("-o", "--output", default=None,
                    help="also write the cpu flame Chrome-trace JSON here")
    pp.set_defaults(fn=cmd_profile)

    pd = sub.add_parser("dashboard", help="serve the HTTP dashboard")
    pd.add_argument("--address", default=None)
    pd.add_argument("--host", default="127.0.0.1")
    pd.add_argument("--port", type=int, default=8265)
    pd.set_defaults(fn=cmd_dashboard)

    args = p.parse_args(argv)
    return args.fn(args)


if __name__ == "__main__":
    sys.exit(main())
