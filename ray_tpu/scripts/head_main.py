"""Detached head process: controller + local node agent.

Spawned by `ray-tpu start --head` (ray_tpu/scripts/cli.py); runs until
SIGTERM/SIGINT. Writes the session file the CLI and joining nodes read.
"""

from __future__ import annotations

import argparse
import json
import os
import signal
import sys
import threading


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--port", type=int, default=6380)
    p.add_argument("--num-cpus", type=float, default=None)
    p.add_argument("--num-tpus", type=float, default=None)
    p.add_argument("--resources", default="{}")
    p.add_argument("--session-dir", required=True)
    p.add_argument("--session", default=None,
                   help="restart into an existing session id (controller FT)")
    args = p.parse_args()

    from ray_tpu._private.bootstrap import HeadNode

    head = HeadNode(num_cpus=args.num_cpus, num_tpus=args.num_tpus,
                    resources=json.loads(args.resources),
                    host=args.host, port=args.port, session_id=args.session)
    addr = head.start()
    os.makedirs(args.session_dir, exist_ok=True)
    with open(os.path.join(args.session_dir, "head.json"), "w") as f:
        json.dump({"address": f"{addr[0]}:{addr[1]}", "pid": os.getpid(),
                   "session": head.session_id}, f)
    print(f"ray-tpu head up at {addr[0]}:{addr[1]}", flush=True)

    stop = threading.Event()

    def _term(signum, frame):
        stop.set()

    signal.signal(signal.SIGTERM, _term)
    signal.signal(signal.SIGINT, _term)
    stop.wait()
    head.stop()


if __name__ == "__main__":
    sys.exit(main())
