"""ray_tpu.workflow — durable workflows with exactly-once step memoization.

Parity target: reference python/ray/workflow (api.py run:123, resume;
workflow continuation/checkpoint semantics over a DAG of tasks). A
workflow is a DAG of `.bind()`ed remote functions; every step's result is
checkpointed to storage under a deterministic step key, so `resume()` (or
simply re-`run`ning the same workflow_id) skips completed steps — the
recovery contract that makes long pipelines restartable.

Step memoization rides the pluggable storage plane (`ray_tpu/storage/`):
`init(storage=...)` accepts any backend URI (`local://`, `mem://`,
`sim://`, a bare path), and every step write is atomic on the backend —
a crash mid-write never half-memoizes a step.
"""

from __future__ import annotations

import hashlib
import os
import pickle
from typing import Any, Optional

import ray_tpu
from ray_tpu import storage as _st

_STORAGE = os.path.expanduser("~/ray_tpu_workflows")


class DAGNode:
    """A bound (fn, args, kwargs) node; args may contain other DAGNodes."""

    def __init__(self, fn, args: tuple, kwargs: dict, name: str):
        self.fn = fn
        self.args = args
        self.kwargs = kwargs
        self.name = name


def bind(remote_fn, *args, **kwargs) -> DAGNode:
    """workflow-step binding for a @ray_tpu.remote function (also exposed
    as RemoteFunction.bind)."""
    inner = getattr(remote_fn, "_fn", remote_fn)
    return DAGNode(remote_fn, args, kwargs,
                   getattr(inner, "__name__", "step"))


def init(storage: Optional[str] = None):
    """Point the workflow store at a storage-plane URI (or local path)."""
    global _STORAGE
    if storage:
        _STORAGE = storage
    _st.makedirs(_STORAGE)


def _step_dir(workflow_id: str) -> str:
    return _st.join(_STORAGE, workflow_id, "steps")


def _hash_const(h, c):
    """Deterministic const digest. repr() is NOT enough: nested code
    objects embed memory addresses and frozenset element order follows
    per-process string-hash randomization — both would silently change a
    step's key on every fresh interpreter and defeat resume."""
    import types

    if isinstance(c, types.CodeType):
        h.update(b"\x01code")
        _hash_code(h, c)
    elif isinstance(c, (frozenset, set)):
        # Length prefix + per-element terminators: without them distinct
        # consts concatenate to identical digest streams ({1,2} vs {12}).
        h.update(b"\x01set%d" % len(c))
        for item in sorted(repr(i) for i in c):
            h.update(item.encode())
            h.update(b"\x00")
    elif isinstance(c, tuple):
        h.update(b"\x01tup%d" % len(c))
        for item in c:
            _hash_const(h, item)
    else:
        h.update(repr(c).encode())
        h.update(b"\x00")


def _hash_code(h, code):
    h.update(code.co_code)
    for c in code.co_consts:
        _hash_const(h, c)


def _step_key(node: DAGNode, child_keys: list[str]) -> str:
    """Deterministic content key: function CODE + literal args + child step
    keys. Same DAG -> same keys across runs, which is what memoization
    keys on; hashing the bytecode (not just the name) means EDITING a
    step's body invalidates its memoized results instead of silently
    replaying stale ones (reference content-addresses via checkpointed
    DAG state)."""
    h = hashlib.sha1()
    h.update(node.name.encode())
    inner = getattr(node.fn, "_fn", node.fn)
    code = getattr(inner, "__code__", None)
    if code is not None:
        _hash_code(h, code)
    for a in list(node.args) + sorted(node.kwargs.items()):
        if isinstance(a, DAGNode):
            continue  # covered by child_keys
        try:
            h.update(pickle.dumps(a))
        except Exception:
            h.update(repr(a).encode())
    for ck in child_keys:
        h.update(ck.encode())
    return f"{node.name}-{h.hexdigest()[:16]}"


def _run_node(node: Any, workflow_id: str, stats: dict):
    if not isinstance(node, DAGNode):
        return node, None
    child_keys = []
    args = []
    for a in node.args:
        v, ck = _run_node(a, workflow_id, stats)
        args.append(v)
        if ck:
            child_keys.append(ck)
    kwargs = {}
    for k, a in node.kwargs.items():
        v, ck = _run_node(a, workflow_id, stats)
        kwargs[k] = v
        if ck:
            child_keys.append(ck)
    key = _step_key(node, child_keys)
    path = _st.join(_step_dir(workflow_id), key + ".pkl")
    if _st.exists(path):
        stats["skipped"] += 1
        return pickle.loads(_st.get_bytes(path)), key
    value = ray_tpu.get(node.fn.remote(*args, **kwargs), timeout=600)
    # Backend puts are atomic: a crash mid-write never half-memoizes.
    _st.put(path, pickle.dumps(value))
    stats["executed"] += 1
    return value, key


def run(dag: DAGNode, *, workflow_id: str) -> Any:
    """Execute the DAG durably; completed steps (from any earlier run of
    this workflow_id) are skipped (reference workflow.run + resume)."""
    init()
    stats = {"executed": 0, "skipped": 0}
    value, _ = _run_node(dag, workflow_id, stats)
    meta = {"workflow_id": workflow_id, "status": "SUCCESSFUL", **stats}
    _st.put(_st.join(_STORAGE, workflow_id, "result.pkl"),
            pickle.dumps({"value": value, "meta": meta}))
    return value


def resume(workflow_id: str, dag: Optional[DAGNode] = None) -> Any:
    """Re-drive a workflow: with the DAG, identical to run (memoization
    does the skipping); without it, return the stored final result."""
    if dag is not None:
        return run(dag, workflow_id=workflow_id)
    path = _st.join(_STORAGE, workflow_id, "result.pkl")
    if not _st.exists(path):
        raise ValueError(f"workflow {workflow_id!r} has no stored result; "
                         f"pass the DAG to resume execution")
    return pickle.loads(_st.get_bytes(path))["value"]


def get_status(workflow_id: str) -> Optional[dict]:
    path = _st.join(_STORAGE, workflow_id, "result.pkl")
    if not _st.exists(path):
        n = len(_st.listdir(_step_dir(workflow_id)))
        return {"workflow_id": workflow_id, "status": "RUNNING" if n else None,
                "steps_done": n}
    return pickle.loads(_st.get_bytes(path))["meta"]


def list_all() -> list[str]:
    return sorted(_st.listdir(_STORAGE))
