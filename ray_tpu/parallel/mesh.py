"""Device mesh construction and sharding helpers.

Replaces (TPU-natively) the reference's process-group bootstrap
(python/ray/train/torch/config.py:66 _setup_torch_process_group — NCCL
rendezvous) and DDP/FSDP wrapping (train/torch/train_loop_utils.py:189):
instead of wrapping modules, we build one `jax.sharding.Mesh` whose named
axes carry every parallelism dimension, annotate arrays with PartitionSpecs,
and let XLA's GSPMD partitioner insert the ICI collectives.

Axis conventions (the scaling-book recipe):
    dp — data parallelism (batch dim; gradient psum)
    fsdp — parameter sharding a la ZeRO-3 (params gathered on use)
    tp — tensor parallelism (matmul output/head dim)
    sp — sequence/context parallelism (sequence dim; ring attention)
    pp — pipeline stages (lax.scan over stages or stage meshes)
    ep — expert parallelism (MoE expert dim; all_to_all routing)
"""

from __future__ import annotations

from dataclasses import dataclass, field

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

AXIS_ORDER = ("dp", "fsdp", "pp", "sp", "tp", "ep")


@dataclass(frozen=True)
class MeshConfig:
    """Degrees for each parallelism axis; -1 on one axis = use remaining
    devices. Axes of degree 1 still exist in the mesh (size-1 axes are free
    in XLA) so PartitionSpecs can always name them."""

    dp: int = -1
    fsdp: int = 1
    pp: int = 1
    sp: int = 1
    tp: int = 1
    ep: int = 1

    def resolve(self, n_devices: int) -> dict[str, int]:
        sizes = {a: getattr(self, a) for a in AXIS_ORDER}
        fixed = 1
        wild = None
        for a, s in sizes.items():
            if s == -1:
                if wild is not None:
                    raise ValueError("only one mesh axis may be -1")
                wild = a
            else:
                fixed *= s
        if wild is not None:
            if n_devices % fixed:
                raise ValueError(f"{n_devices} devices not divisible by {fixed}")
            sizes[wild] = n_devices // fixed
        elif fixed != n_devices:
            raise ValueError(f"mesh {sizes} needs {fixed} devices, have {n_devices}")
        return sizes


def build_mesh(config: MeshConfig | None = None, devices=None) -> Mesh:
    """Build a Mesh over the given (default: all) devices.

    On real TPU slices, `jax.devices()` ordering already follows the
    physical torus, so contiguous reshape keeps ICI-neighbor axes adjacent;
    `jax.experimental.mesh_utils.create_device_mesh` is used when available
    for a topology-aware layout.
    """
    config = config or MeshConfig()
    devices = list(devices if devices is not None else jax.devices())
    sizes = config.resolve(len(devices))
    shape = tuple(sizes[a] for a in AXIS_ORDER)
    try:
        from jax.experimental import mesh_utils

        dev_array = mesh_utils.create_device_mesh(shape, devices=devices)
    except Exception:
        dev_array = np.asarray(devices).reshape(shape)
    return Mesh(dev_array, AXIS_ORDER)


def local_mesh(n: int | None = None, axis: str = "dp") -> Mesh:
    """1-axis mesh over the first n local devices (tests, single-host)."""
    devices = jax.devices()[: n or len(jax.devices())]
    return Mesh(np.asarray(devices), (axis,))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def data_sharding(mesh: Mesh, *, batch_axes: tuple[str, ...] = ("dp", "fsdp"),
                  seq_axis: str | None = None) -> NamedSharding:
    """Batch sharded over the data axes; optionally sequence over sp.
    For [batch, seq, ...] inputs."""
    if seq_axis:
        return NamedSharding(mesh, P(batch_axes, seq_axis))
    return NamedSharding(mesh, P(batch_axes))


def shard_params(params, specs, mesh: Mesh):
    """Place a parameter pytree according to a matching PartitionSpec pytree
    (device_put with NamedShardings — the GSPMD analogue of FSDP/DeepSpeed
    parameter sharding, reference train_loop_utils.py:189)."""
    return jax.tree_util.tree_map(
        lambda x, s: jax.device_put(x, NamedSharding(mesh, s)), params, specs,
        is_leaf=lambda x: x is None,
    )


def spec_tree_like(params, fn):
    """Build a PartitionSpec tree by calling fn(path, leaf) over params."""
    flat, treedef = jax.tree_util.tree_flatten_with_path(params)
    specs = [fn(tuple(str(getattr(k, "key", getattr(k, "idx", k))) for k in path), leaf)
             for path, leaf in flat]
    return jax.tree_util.tree_unflatten(treedef, specs)
