"""Pipeline parallelism: GPipe-style microbatched schedule over the "pp"
mesh axis.

TPU-native design: the block stack's parameters carry a leading [n_layers]
axis sharded over pp, so each device physically holds only its stage's
layers. Under shard_map, every pipeline tick applies the local stage to the
activation in flight and `ppermute`s it to the next stage; `lax.scan` rolls
the schedule into one compiled program and autodiff reverses the ring for
the backward pass (the transpose of ppermute is the reverse permute — the
backward pipeline comes for free). With M microbatches and S stages the
bubble is the standard (S-1)/(M+S-1).

The reference delegates PP to vLLM (llm/_internal/serve/.../vllm_models.py
passthrough); there is no reference code to mirror — this is designed
fresh for the XLA compilation model (SURVEY §7 step 11 peer).
"""

from __future__ import annotations

import functools
from dataclasses import dataclass

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ray_tpu.ops.attention import _xla_attention


@dataclass(frozen=True)
class PipelineConfig:
    vocab_size: int = 512
    d_model: int = 128
    n_layers: int = 4  # total, split evenly across pp stages
    n_heads: int = 4
    d_ff: int = 256
    n_microbatches: int = 4


def init_params(cfg: PipelineConfig, seed: int = 0) -> dict:
    """Raw-pytree params; block weights stacked on a leading [n_layers]
    axis (the axis pp shards)."""
    rng = np.random.RandomState(seed)
    L, D, F, H = cfg.n_layers, cfg.d_model, cfg.d_ff, cfg.n_heads

    def w(*shape, scale=None):
        scale = scale or (1.0 / np.sqrt(shape[-2] if len(shape) > 1 else shape[0]))
        return jnp.asarray(rng.randn(*shape) * scale, jnp.float32)

    return {
        "emb": w(cfg.vocab_size, D, scale=0.02),
        "blocks": {
            "wq": w(L, D, D), "wk": w(L, D, D), "wv": w(L, D, D),
            "wo": w(L, D, D),
            "w_gate": w(L, D, F), "w_up": w(L, D, F), "w_down": w(L, F, D),
            "norm1": jnp.ones((L, D), jnp.float32),
            "norm2": jnp.ones((L, D), jnp.float32),
        },
        "final_norm": jnp.ones((D,), jnp.float32),
    }


def _rms(x, scale):
    n = x * jax.lax.rsqrt(jnp.mean(x * x, axis=-1, keepdims=True) + 1e-6)
    return n * scale


def _block(bp, x, n_heads: int):
    """One transformer block with single-layer params bp (no leading axis)."""
    b, s, d = x.shape
    hd = d // n_heads
    h = _rms(x, bp["norm1"])
    q = (h @ bp["wq"]).reshape(b, s, n_heads, hd)
    k = (h @ bp["wk"]).reshape(b, s, n_heads, hd)
    v = (h @ bp["wv"]).reshape(b, s, n_heads, hd)
    att = _xla_attention(q, k, v, causal=True).reshape(b, s, d)
    x = x + att @ bp["wo"]
    h = _rms(x, bp["norm2"])
    x = x + (jax.nn.silu(h @ bp["w_gate"]) * (h @ bp["w_up"])) @ bp["w_down"]
    return x


def _stage_apply(stage_blocks, x, n_heads: int):
    """Apply this device's layers_per_stage blocks (leading axis scanned)."""

    def body(carry, bp):
        return _block(bp, carry, n_heads), None

    out, _ = jax.lax.scan(body, x, stage_blocks)
    return out


def _pipeline_shard_fn(blocks, x_mb, cfg: PipelineConfig, n_stages: int):
    """Runs under shard_map over 'pp'. blocks: this stage's slice (leading
    axis = layers_per_stage). x_mb: [M, mb, S, D] microbatched embeddings
    (replicated). Returns [M, mb, S, D] block-stack outputs (valid on the
    LAST stage; zeros elsewhere — caller psums over pp)."""
    stage = jax.lax.axis_index("pp")
    M = cfg.n_microbatches
    T = M + n_stages - 1
    mb_shape = x_mb.shape[1:]

    perm_fwd = [(i, i + 1) for i in range(n_stages - 1)]

    def tick(carry, t):
        buf = carry  # activation arriving from the previous stage
        inject = x_mb[jnp.clip(t, 0, M - 1)]
        cur = jnp.where(stage == 0, inject, buf)
        y = _stage_apply(blocks, cur, cfg.n_heads)
        nxt = jax.lax.ppermute(y, "pp", perm_fwd)
        return nxt, y

    zero = jnp.zeros(mb_shape, x_mb.dtype)
    for _mark in (lambda x: jax.lax.pcast(x, to="varying"),
                  lambda x: jax.lax.pvary(x, "pp"),
                  lambda x: x):
        # Marking API differs across jax versions (pcast / pvary); builds
        # with NEITHER (<=0.4.x) don't type-check carry variance under
        # shard_map (check_rep=False above), so identity is correct there.
        try:
            zero = _mark(zero)
            break
        except (AttributeError, TypeError):
            continue
    _, ys = jax.lax.scan(tick, zero, jnp.arange(T))
    # On the last stage, ys[t] for t in [S-1, S-1+M) are microbatches 0..M-1.
    outs = jax.lax.dynamic_slice_in_dim(ys, n_stages - 1, M, axis=0)
    outs = jnp.where(stage == n_stages - 1, outs, 0.0)
    # Broadcast the finished activations to every stage for the (replicated)
    # head: zeros elsewhere make this a plain psum.
    return jax.lax.psum(outs, "pp")


def pipeline_loss_fn(cfg: PipelineConfig, mesh: Mesh):
    """Returns loss(params, tokens) whose block stack runs as a GPipe
    pipeline over the mesh's pp axis (embedding/head replicated)."""
    from jax.experimental.shard_map import shard_map

    n_stages = mesh.shape["pp"]
    assert cfg.n_layers % n_stages == 0

    pipe = shard_map(
        functools.partial(_pipeline_shard_fn, cfg=cfg, n_stages=n_stages),
        mesh=mesh,
        in_specs=(P("pp"), P()),   # blocks stage-sharded; microbatches replicated
        out_specs=P(),
        check_rep=False,
    )

    def loss_fn(params, tokens):
        x = params["emb"][tokens[:, :-1]]  # [B, S, D]
        b, s, d = x.shape
        M = cfg.n_microbatches
        assert b % M == 0
        x_mb = x.reshape(M, b // M, s, d)
        y_mb = pipe(params["blocks"], x_mb)
        y = y_mb.reshape(b, s, d)
        y = _rms(y, params["final_norm"])
        logits = y @ params["emb"].T
        targets = tokens[:, 1:]
        logp = jax.nn.log_softmax(logits, axis=-1)
        nll = -jnp.take_along_axis(logp, targets[..., None], axis=-1)[..., 0]
        return nll.mean()

    return loss_fn


def reference_loss(cfg: PipelineConfig, params, tokens):
    """Single-device sequential apply of the same stacked params."""
    x = params["emb"][tokens[:, :-1]]
    x = _stage_apply(params["blocks"], x, cfg.n_heads)
    x = _rms(x, params["final_norm"])
    logits = x @ params["emb"].T
    targets = tokens[:, 1:]
    logp = jax.nn.log_softmax(logits, axis=-1)
    return -jnp.take_along_axis(logp, targets[..., None], axis=-1)[..., 0].mean()
