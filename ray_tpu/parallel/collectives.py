"""Device-tier collectives: in-program XLA collectives over mesh axes.

The TPU-native replacement for the reference's NCCL groups
(util/collective/collective_group/nccl_collective_group.py,
experimental/channel/nccl_group.py:22): instead of out-of-band process
groups, collective math is expressed inside compiled programs with
`jax.lax` primitives under `shard_map`, and XLA lowers them to ICI
transfers. These helpers wrap the common patterns so library code (Train
learners, ring attention) doesn't repeat shard_map boilerplate.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

_shard_map_raw = jax.shard_map if hasattr(jax, "shard_map") else None
if _shard_map_raw is None:  # pragma: no cover — older jax
    from jax.experimental.shard_map import shard_map as _shard_map_raw  # type: ignore


def shard_map(f, *, mesh, in_specs, out_specs, check_vma: bool = False):
    """jax.shard_map with the static-replication check relaxed by default:
    collective-heavy bodies (all_gather -> replicated out) routinely defeat
    the inference and the runtime sharding is still checked."""
    try:
        return _shard_map_raw(f, mesh=mesh, in_specs=in_specs,
                              out_specs=out_specs, check_vma=check_vma)
    except TypeError:  # pragma: no cover — pre-0.8 jax called it check_rep
        return _shard_map_raw(f, mesh=mesh, in_specs=in_specs,
                              out_specs=out_specs, check_rep=check_vma)


def psum(x, axis_name: str):
    """Inside shard_map/pjit: sum across a mesh axis (ICI allreduce)."""
    return jax.lax.psum(x, axis_name)


def pmean(x, axis_name: str):
    return jax.lax.pmean(x, axis_name)


def all_gather(x, axis_name: str, axis: int = 0, tiled: bool = True):
    return jax.lax.all_gather(x, axis_name, axis=axis, tiled=tiled)


def ppermute_ring(x, axis_name: str, mesh: Mesh, shift: int = 1):
    """Rotate shards one step around the axis ring (the primitive under
    ring attention / pipeline handoff)."""
    n = mesh.shape[axis_name]
    perm = [(i, (i + shift) % n) for i in range(n)]
    return jax.lax.ppermute(x, axis_name, perm)


def all_to_all(x, axis_name: str, split_axis: int, concat_axis: int):
    return jax.lax.all_to_all(x, axis_name, split_axis, concat_axis, tiled=True)


def mesh_allreduce(mesh: Mesh, x, axis_name: str = "dp"):
    """Whole-array allreduce over one mesh axis, runnable from host code:
    jit(shard_map(psum)). For gradient sync when not already inside a pjit
    program (the common JaxTrainer DP path runs psum inside the train step
    instead — this is the standalone utility)."""
    spec = P(axis_name)
    fn = shard_map(
        functools.partial(jax.lax.psum, axis_name=axis_name),
        mesh=mesh, in_specs=spec, out_specs=P())

    sharded = jax.device_put(x, NamedSharding(mesh, spec))
    return jax.jit(fn)(sharded)
