"""TPU-native parallelism layer: device meshes, shardings, collectives.

This is the subsystem that replaces the reference's NCCL/GLOO process-group
world (python/ray/util/collective/, python/ray/train/torch/config.py:66
_setup_torch_process_group, python/ray/experimental/channel/nccl_group.py):
on TPU, collective math lives *inside* compiled XLA programs as psum /
all_gather / ppermute / all_to_all over the ICI torus, orchestrated by
`jax.sharding.Mesh` + pjit — not as out-of-band process-group calls.
"""

from ray_tpu.parallel.mesh import (
    MeshConfig,
    build_mesh,
    data_sharding,
    local_mesh,
    replicated,
    shard_params,
)

__all__ = [
    "MeshConfig",
    "build_mesh",
    "local_mesh",
    "data_sharding",
    "replicated",
    "shard_params",
]
