"""Train controller: drives the worker group, drains reports, applies the
failure policy.

Parity target: reference train v2 controller
(train/v2/_internal/execution/controller/controller.py:91 TrainController,
run:446, loop :423) with FailurePolicy (failure_policy.py:14): on a
worker-group failure, if the policy allows, the whole group is torn down and
restarted from the latest reported checkpoint (elastic recovery).
"""

from __future__ import annotations

import logging
import os
import time
import uuid
from typing import Optional

import ray_tpu
from ray_tpu import storage
from ray_tpu.train import checkpoint as ckpt_mod
from ray_tpu.train._internal.worker_group import WorkerGroup
from ray_tpu.train.checkpoint import Checkpoint
from ray_tpu.train.config import FailureConfig, RunConfig, ScalingConfig

logger = logging.getLogger(__name__)


class Result:
    """reference python/ray/air/result.py Result."""

    def __init__(self, metrics: Optional[dict], checkpoint: Optional[Checkpoint],
                 path: str, error: Optional[str] = None,
                 metrics_history: Optional[list] = None):
        self.metrics = metrics
        self.checkpoint = checkpoint
        self.path = path
        self.error = error
        self.metrics_history = metrics_history or []

    def __repr__(self):
        return (f"Result(metrics={self.metrics}, checkpoint={self.checkpoint}, "
                f"error={'yes' if self.error else None})")


class TrainController:
    def __init__(self, *, train_fn, train_loop_config,
                 scaling_config: ScalingConfig, run_config: RunConfig,
                 datasets: Optional[dict] = None):
        self.train_fn = train_fn
        self.config = train_loop_config
        self.scaling = scaling_config
        self.run_config = run_config
        self.datasets = datasets or {}
        self.run_name = run_config.name or f"train_{uuid.uuid4().hex[:8]}"
        # storage_path may be any storage-plane URI (local path, local://,
        # mem://, sim://) — every durable byte below rides the backend.
        self.storage_dir = storage.join(run_config.resolved_storage(),
                                        self.run_name)
        storage.makedirs(self.storage_dir)
        self.latest_checkpoint: Optional[Checkpoint] = None
        self.metrics_history: list[dict] = []
        self._checkpoint_paths: list[str] = []
        self.failures = 0

    def _elastic_size(self) -> int:
        """Workers for the NEXT attempt (reference train v2 ScalingPolicy's
        elastic recovery decision): fixed groups always ask for num_workers;
        elastic groups (min_workers set) size to what the cluster can place
        right now, clamped to [min_workers, num_workers]."""
        want = self.scaling.num_workers
        lo = self.scaling.min_workers
        if lo is None or lo >= want:
            return want
        try:
            from ray_tpu._private.rtconfig import CONFIG
            from ray_tpu._private.worker import global_worker

            # Size against nodes that have beaten SINCE we started looking:
            # a node that died moments ago still shows alive (and its last
            # beat still looks recent) until the detection timeout, and
            # sizing against it would hang the restart on actors that can
            # never place. Waiting two beat intervals and requiring
            # beat_age < elapsed admits exactly the nodes with fresh
            # evidence of life — a ~1s pause instead of the previous
            # full-detection-window sleep (10s) on this thread.
            t0 = time.monotonic()
            time.sleep(CONFIG.heartbeat_interval_s * 2 + 0.2)
            elapsed = time.monotonic() - t0
            snap = global_worker().state_snapshot()
            avail: dict[str, float] = {}
            for n in snap["nodes"].values():
                if not n["alive"] or n.get("beat_age", 0.0) > elapsed:
                    continue
                for k, v in n["available"].items():
                    avail[k] = avail.get(k, 0.0) + v
        except Exception:
            return want
        per = self.scaling.worker_resources()
        fits = min((int(avail.get(k, 0.0) // v) for k, v in per.items() if v),
                   default=want)
        return max(1, lo, min(want, fits))

    def _split_datasets(self, n: int) -> Optional[list]:
        if not self.datasets:
            return None
        shards = [dict() for _ in range(n)]
        for name, ds in self.datasets.items():
            if hasattr(ds, "streaming_split"):
                for rank, piece in enumerate(ds.streaming_split(n)):
                    shards[rank][name] = piece
            else:
                for rank in range(n):
                    shards[rank][name] = ds
        return shards

    def run(self) -> Result:
        max_failures = self.run_config.failure_config.max_failures
        attempt = 0
        while True:
            n_workers = (self.scaling.num_workers if attempt == 0
                         else self._elastic_size())
            if attempt > 0 and n_workers != self.scaling.num_workers:
                logger.warning("elastic restart with %d/%d workers",
                               n_workers, self.scaling.num_workers)
            try:
                group = WorkerGroup(
                    num_workers=n_workers,
                    resources_per_worker=self.scaling.worker_resources(),
                    run_name=self.run_name,
                    storage_dir=self.storage_dir,
                    group_name=f"train-{self.run_name}-r{attempt}",
                    restart_index=attempt,
                    latest_checkpoint=self.latest_checkpoint,
                    dataset_shards_per_worker=self._split_datasets(n_workers),
                    jax_distributed=self.scaling.jax_distributed,
                    worker_env=self.scaling.worker_env,
                )
            except Exception as e:
                # Group start failure goes through the same failure policy
                # as a mid-run crash (the group cleaned itself up).
                outcome = {"status": "system_failure", "error": f"group start failed: {e!r}"}
            else:
                try:
                    outcome = self._run_attempt(group)
                finally:
                    group.shutdown()
            if outcome["status"] == "finished":
                return Result(
                    metrics=self.metrics_history[-1] if self.metrics_history else None,
                    checkpoint=self.latest_checkpoint,
                    path=self.storage_dir,
                    metrics_history=self.metrics_history,
                )
            if outcome["status"] == "user_error":
                return Result(
                    metrics=self.metrics_history[-1] if self.metrics_history else None,
                    checkpoint=self.latest_checkpoint,
                    path=self.storage_dir,
                    error=outcome["error"],
                    metrics_history=self.metrics_history,
                )
            # system failure -> failure policy (reference failure_policy.py:14)
            self.failures += 1
            attempt += 1
            if max_failures != -1 and self.failures > max_failures:
                return Result(
                    metrics=self.metrics_history[-1] if self.metrics_history else None,
                    checkpoint=self.latest_checkpoint,
                    path=self.storage_dir,
                    error=f"training failed after {self.failures} failures: "
                          f"{outcome['error']}",
                    metrics_history=self.metrics_history,
                )
            logger.warning("train group failure %d (%s); restarting from %s",
                           self.failures, outcome["error"], self.latest_checkpoint)
            try:
                from ray_tpu._private.events import emit_event

                emit_event(
                    "train_restart",
                    f"train group {self.run_name!r} failure "
                    f"{self.failures} ({str(outcome['error'])[:120]}); "
                    f"restarting from "
                    f"{getattr(self.latest_checkpoint, 'path', None)}",
                    entity=(self.run_name,),
                    attrs={"failures": self.failures, "attempt": attempt})
            except Exception:
                pass

    def _drain(self, group: WorkerGroup) -> int:
        """Drain worker reports into history; returns how many landed —
        the group-stall policy's definition of committed progress."""
        n = 0
        for p in group.poll():
            for rep in p["reports"]:
                n += 1
                self.metrics_history.append(rep["metrics"])
                if rep.get("checkpoint_path"):
                    self.latest_checkpoint = Checkpoint(rep["checkpoint_path"])
                    self._checkpoint_paths.append(rep["checkpoint_path"])
                    self._prune_checkpoints()
        return n

    def _prune_checkpoints(self):
        keep = self.run_config.checkpoint_config.num_to_keep
        if not keep:
            return
        while len(self._checkpoint_paths) > keep:
            victim = self._checkpoint_paths[0]
            if self.latest_checkpoint and victim == self.latest_checkpoint.path:
                self._checkpoint_paths.pop(0)
                continue
            # Backend delete, pin-aware: a checkpoint some other consumer
            # pinned (e.g. a Tune PBT clone restoring from this run)
            # survives until its last owner unpins — it stays TRACKED so a
            # later prune pass (the next report) retries the delete.
            try:
                if not ckpt_mod.delete_checkpoint(victim):
                    break  # oldest victim is pinned; retry next prune
            except Exception:
                logger.exception("checkpoint prune failed for %s", victim)
            self._checkpoint_paths.pop(0)

    def _report_group_stall(self, silent_s: float, stall_timeout: float):
        """Surface the group stall through the cluster's stall plane
        (util.state.list_stalls / rt_stalls_total) before the kill."""
        try:
            from ray_tpu._private.worker import global_worker

            w = global_worker()
            if w is None:
                return
            w.controller.push_threadsafe("stall_report", report={
                "scope": "train_group", "stage": "kill",
                "task_id": None, "name": self.run_name, "attempt": None,
                "kind": "train", "worker_id": None, "node_id": None,
                "pid": os.getpid(), "silence_s": round(silent_s, 3),
                "time": time.time(),
                "reason": (f"train group {self.run_name!r} committed no "
                           f"progress for {silent_s:.1f}s (stall_timeout_s="
                           f"{stall_timeout}); killing the group and "
                           f"restarting from the latest committed "
                           f"checkpoint"),
                "events": [], "flight_dir": None,
            })
        except Exception:
            pass

    def _run_attempt(self, group: WorkerGroup) -> dict:
        stall_timeout = self.run_config.failure_config.stall_timeout_s
        run_refs = group.run_async(self.train_fn, self.config)
        pending = list(run_refs)
        last_progress = time.monotonic()
        while pending:
            done, pending = ray_tpu.wait(pending, num_returns=len(pending), timeout=0.2)
            if self._drain(group):
                last_progress = time.monotonic()
            if stall_timeout and not done:
                silent = time.monotonic() - last_progress
                if silent > stall_timeout:
                    # Silent hang: workers alive, sockets open, nothing
                    # reporting. Treat as a group failure — the caller
                    # tears the group down and the failure policy restarts
                    # from the latest COMMITTED checkpoint (PR 8 releases
                    # report entries only on commit, so the restore point
                    # is always durable).
                    self._report_group_stall(silent, stall_timeout)
                    return {"status": "system_failure",
                            "error": f"train group stalled: no worker "
                                     f"reported progress for {silent:.1f}s "
                                     f"(stall_timeout_s={stall_timeout})"}
            for ref in done:
                try:
                    out = ray_tpu.get(ref, timeout=30)
                except Exception as e:  # actor/worker/system death
                    self._drain(group)
                    return {"status": "system_failure", "error": repr(e)}
                if not out["ok"]:
                    self._drain(group)
                    return {"status": "user_error", "error": out["error"]}
                last_progress = time.monotonic()
        self._drain(group)
        return {"status": "finished"}
