"""Worker group: the actor fleet a trainer runs on.

Parity target: reference python/ray/train/_internal/worker_group.py
(WorkerGroup:102, start:193, execute_async:233) + the v2 worker group
(train/v2/_internal/execution/worker_group/worker_group.py:103).
"""

from __future__ import annotations

import traceback
from typing import Optional

import ray_tpu
from ray_tpu import storage
from ray_tpu.train._internal import session as session_mod


@ray_tpu.remote
class TrainWorkerActor:
    """Hosts one training worker. max_concurrency=2 in practice (set via
    .options at creation) so the controller can poll reports while the
    user's train loop occupies the other thread."""

    def __init__(self):
        self._error: Optional[str] = None

    def setup(self, *, rank: int, world_size: int, local_rank: int, node_rank: int,
              run_name: str, storage_dir: str, restart_index: int,
              latest_checkpoint, group_name: str, dataset_shards=None,
              jax_distributed: bool = False):
        session_mod.init_session(
            rank=rank, world_size=world_size, local_rank=local_rank,
            node_rank=node_rank, run_name=run_name, storage_dir=storage_dir,
            restart_index=restart_index, latest_checkpoint=latest_checkpoint,
            dataset_shards=dataset_shards, group_name=group_name)
        # Host-tier collective rendezvous for DP gradient sync across
        # workers (role of reference _setup_torch_process_group,
        # train/torch/config.py:66 — NCCL/GLOO init replaced by the
        # control-plane collective group + in-program ICI collectives).
        from ray_tpu.util import collective

        collective.init_collective_group(world_size, rank, group_name)
        if jax_distributed:
            # One global jax mesh over every worker's devices: rank 0 hosts
            # the coordinator; the address rendezvous rides the controller
            # KV (role of the reference's torch dist init_method).
            from ray_tpu.train import jax_utils

            jax_utils.setup_jax_distributed(group_name, rank, world_size)
        return True

    def run(self, train_fn, config):
        s = session_mod.get_session()
        try:
            # Accept 0- or 1-arg loops (reference train_loop_per_worker
            # signature inspection, data_parallel_trainer.py).
            import inspect

            takes_config = len(inspect.signature(train_fn).parameters) >= 1
            result = train_fn(config) if takes_config else train_fn()
            # Async checkpoint saves release their report entries on
            # commit: make every one durable+visible before the
            # controller's final drain.
            s.flush_checkpoints()
            s.finished = True
            return {"ok": True, "result": result}
        except BaseException:
            try:
                s.flush_checkpoints()
            except Exception:
                pass
            s.finished = True
            return {"ok": False, "error": traceback.format_exc()}

    def poll(self):
        s = session_mod.get_session()
        return {"reports": s.drain_reports(), "finished": s.finished}

    def shutdown(self):
        session_mod.shutdown_session()
        return True


class WorkerGroup:
    def __init__(self, *, num_workers: int, resources_per_worker: dict,
                 run_name: str, storage_dir: str, group_name: str,
                 restart_index: int = 0, latest_checkpoint=None,
                 dataset_shards_per_worker: Optional[list] = None,
                 jax_distributed: bool = False,
                 worker_env: Optional[dict] = None):
        self.num_workers = num_workers
        self.workers = []
        res = dict(resources_per_worker)
        opts = {"num_cpus": res.pop("CPU", 0), "max_concurrency": 4}
        if res.pop("TPU", 0):
            opts["num_tpus"] = resources_per_worker["TPU"]
        if res:
            opts["resources"] = res
        env_vars = dict(worker_env or {})
        # Stall-watchdog escalation dumps from these workers land under the
        # RUN's storage (<run>/flight/), not the node's session dir — they
        # must survive the worker AND travel with the run's artifacts. Only
        # injected while the escalation ladder is actually armed (the
        # resolved config propagates cluster-wide), so a default run's
        # worker env stays untouched.
        from ray_tpu._private import watchdog

        if watchdog.enabled():
            env_vars.setdefault("RT_STALL_FLIGHT_DIR",
                                storage.join(storage_dir, "flight"))
        if env_vars:
            # Applied at worker-process spawn, BEFORE any import runs there
            # (XLA_FLAGS etc. must precede the first jax import).
            opts["runtime_env"] = {"env_vars": env_vars}
        try:
            for rank in range(num_workers):
                self.workers.append(TrainWorkerActor.options(**opts).remote())
            setup_refs = []
            for rank, w in enumerate(self.workers):
                shards = (dataset_shards_per_worker[rank]
                          if dataset_shards_per_worker else None)
                setup_refs.append(w.setup.remote(
                    rank=rank, world_size=num_workers, local_rank=rank,
                    node_rank=0, run_name=run_name, storage_dir=storage_dir,
                    restart_index=restart_index, latest_checkpoint=latest_checkpoint,
                    group_name=group_name, dataset_shards=shards,
                    jax_distributed=jax_distributed))
            ray_tpu.get(setup_refs, timeout=300)
        except BaseException:
            # A failed start must not strand the actors it already created.
            self.shutdown()
            raise

    def run_async(self, train_fn, config) -> list:
        return [w.run.remote(train_fn, config) for w in self.workers]

    def poll(self) -> list[dict]:
        """Poll every worker in ONE batched `ray_tpu.get(refs)` (the old
        per-ref loop gathered serially: worker k's result waited on k-1
        slow pollers even when already resolved). Worker-returned arrays
        (checkpoint shards, eval tensors) ride device refs automatically
        when the plane is on — poll reports themselves are small dicts.
        Failure isolation is preserved: if the batch raises, fall back to
        per-ref gets so a dead worker loses only ITS reports."""
        refs = [w.poll.remote() for w in self.workers]
        try:
            return list(ray_tpu.get(refs, timeout=60))
        except Exception:
            out = []
            for ref in refs:
                try:
                    out.append(ray_tpu.get(ref, timeout=60))
                except Exception:
                    pass
            return out

    def shutdown(self):
        for w in self.workers:
            try:
                ray_tpu.kill(w)
            except Exception:
                pass
        self.workers = []
