"""Per-worker train session.

Parity target: reference python/ray/train/_internal/session.py
(_TrainSession:112, report:672, get_checkpoint:786, get_dataset_shard:1114).
The session is the worker-side half of the trainer: it knows this worker's
rank/world, buffers report() payloads for the controller to drain, persists
checkpoints into run storage THROUGH the storage plane
(`ray_tpu/storage/`, README "Checkpointing & storage"), and hands out
dataset shards.

Checkpoint flow: `report(checkpoint=...)` accepts either a directory
`Checkpoint` (rank 0 uploads it) or a state pytree (EVERY rank saves its
local shards via the async engine; rank 0 commits the manifest). With
RT_CKPT_ASYNC=1 (default) the upload runs off the training step on the
engine's writer thread, and the report entry is released to the controller
only when the checkpoint COMMITS — the controller can never restart a
failed run from a checkpoint that wasn't durable yet.
"""

from __future__ import annotations

import logging
import threading
from typing import Any, Optional

from ray_tpu import storage
from ray_tpu._private import watchdog
from ray_tpu.train import checkpoint as ckpt_mod
from ray_tpu.train.checkpoint import Checkpoint

logger = logging.getLogger(__name__)

_session: Optional["TrainSession"] = None
_session_lock = threading.Lock()


class TrainContext:
    """What user code sees via ray_tpu.train.get_context() (reference
    train/context.py TrainContext)."""

    def __init__(self, session: "TrainSession"):
        self._s = session

    def get_world_rank(self) -> int:
        return self._s.rank

    def get_world_size(self) -> int:
        return self._s.world_size

    def get_local_rank(self) -> int:
        return self._s.local_rank

    def get_node_rank(self) -> int:
        return self._s.node_rank

    def get_trial_name(self) -> str:
        return self._s.run_name

    def get_experiment_name(self) -> str:
        return self._s.run_name

    def get_storage(self) -> str:
        return self._s.storage_dir


class TrainSession:
    def __init__(self, *, rank: int, world_size: int, local_rank: int,
                 node_rank: int, run_name: str, storage_dir: str,
                 restart_index: int, latest_checkpoint: Optional[Checkpoint],
                 dataset_shards: Optional[dict] = None, group_name: str = "default"):
        self.group_name = group_name
        self.rank = rank
        self.world_size = world_size
        self.local_rank = local_rank
        self.node_rank = node_rank
        self.run_name = run_name
        self.storage_dir = storage_dir
        self.restart_index = restart_index
        self.latest_checkpoint = latest_checkpoint
        self.dataset_shards = dataset_shards or {}
        self.reports: list[dict] = []  # drained by the controller
        self.reports_lock = threading.Lock()
        self.report_seq = 0
        self.finished = False
        # (SaveHandle, entry) awaiting commit; guarded by _saves_lock (the
        # train loop reports while the controller's poll thread drains).
        self._pending_saves: list = []
        self._saves_lock = threading.Lock()
        # Serializes queue drains (and the direct-append fast path) so two
        # threads can never interleave released entries out of order.
        self._reap_lock = threading.Lock()

    # ------------------------------------------------------------- user API
    def report(self, metrics: dict, checkpoint=None):
        """reference session.py:672 — metrics to the controller; checkpoint
        persisted rank-aware through the storage backend. `checkpoint` is a
        directory Checkpoint (rank 0 owns the canonical copy) or a state
        pytree (sharded save: every rank writes its local shards)."""
        # Every report IS progress: tick this worker's stall beacon so a
        # healthy-but-slow step never trips the per-task watchdog while a
        # loop that stops calling report() eventually does.
        watchdog.report_progress()
        entry: dict[str, Any] = {"metrics": dict(metrics), "rank": self.rank}
        if checkpoint is None:
            # Queue behind any in-flight saves (handle=None releases as
            # soon as it reaches the front) — reports must reach the
            # controller in the order the loop made them, or the run's
            # FINAL metrics could be an older step's. The reap lock keeps
            # the emptiness check atomic with any in-progress drain.
            with self._reap_lock:
                with self._saves_lock:
                    if self._pending_saves:
                        self._pending_saves.append((None, entry))
                        return
                self._append(entry)
            return
        self.report_seq += 1
        # Namespaced by restart attempt: a resumed run must never write
        # onto an earlier attempt's checkpoint dirs.
        dest = storage.join(
            self.storage_dir, "checkpoints",
            f"checkpoint_r{self.restart_index}_{self.report_seq:06d}")
        if isinstance(checkpoint, Checkpoint):
            if self.rank != 0:
                self._append(entry)
                return
            # as_directory: a local dir passes through; a URI checkpoint
            # materializes first. Contents are buffered before the
            # context exits, so temp sources may vanish right after.
            with checkpoint.as_directory() as src:
                handle = ckpt_mod.upload_directory_async(
                    src, dest, step=self.report_seq)
        else:
            handle = ckpt_mod.save_async(
                checkpoint, dest, step=self.report_seq, rank=self.rank,
                world_size=self.world_size)
        if self.rank == 0:
            entry["checkpoint_path"] = dest
        if handle.done():
            # Sync mode (RT_CKPT_ASYNC=0): committed before report returns.
            handle.result()  # surface save failures to the train loop
            self._append(entry)
        else:
            # Async: hold the WHOLE entry until the save commits, then
            # release it — the controller only ever sees durable
            # checkpoints, and entries stay in report order (the engine
            # writer is single-threaded FIFO).
            with self._saves_lock:
                self._pending_saves.append((handle, entry))
            self._reap_pending(block=False)

    def _append(self, entry: dict):
        with self.reports_lock:
            self.reports.append(entry)

    def _reap_pending(self, block: bool, timeout: Optional[float] = None):
        """Release queued entries in strict FIFO order: drain the prefix
        whose saves committed (entries with no save release when reached);
        in blocking mode wait for all of them (end of the run / explicit
        flush). A failed save drops its checkpoint_path (metrics still
        flow) — the controller keeps restoring from the previous committed
        checkpoint."""
        with self._reap_lock:
            while True:
                with self._saves_lock:
                    if not self._pending_saves:
                        return
                    handle, entry = self._pending_saves[0]
                    if handle is not None and not block and not handle.done():
                        return
                    self._pending_saves.pop(0)
                if handle is not None:
                    try:
                        handle.result(timeout)
                    except Exception:
                        logger.exception(
                            "checkpoint save failed (rank %d, %s); reporting "
                            "metrics without it", self.rank, handle.uri)
                        entry.pop("checkpoint_path", None)
                self._append(entry)

    def flush_checkpoints(self, timeout: float = 300.0):
        """Wait for every in-flight async save to commit and release its
        report entry (called by the worker actor when the train loop
        returns — the controller's final drain must see the last
        checkpoint)."""
        self._reap_pending(block=True, timeout=timeout)

    def get_checkpoint(self) -> Optional[Checkpoint]:
        return self.latest_checkpoint

    def get_dataset_shard(self, name: str = "train"):
        shard = self.dataset_shards.get(name)
        if shard is None:
            raise KeyError(f"no dataset shard named {name!r}; pass datasets= to the trainer")
        return shard

    def drain_reports(self) -> list[dict]:
        self._reap_pending(block=False)
        with self.reports_lock:
            out = self.reports
            self.reports = []
        return out


def init_session(**kw) -> TrainSession:
    global _session
    with _session_lock:
        _session = TrainSession(**kw)
    return _session


def get_session() -> TrainSession:
    if _session is None:
        raise RuntimeError(
            "No train session in this process — are you inside train_loop_per_worker?")
    return _session


def shutdown_session():
    global _session
    with _session_lock:
        _session = None
