"""Per-worker train session.

Parity target: reference python/ray/train/_internal/session.py
(_TrainSession:112, report:672, get_checkpoint:786, get_dataset_shard:1114).
The session is the worker-side half of the trainer: it knows this worker's
rank/world, buffers report() payloads for the controller to drain, persists
checkpoints into run storage, and hands out dataset shards.
"""

from __future__ import annotations

import os
import shutil
import threading
from typing import Any, Optional

from ray_tpu.train.checkpoint import Checkpoint

_session: Optional["TrainSession"] = None
_session_lock = threading.Lock()


class TrainContext:
    """What user code sees via ray_tpu.train.get_context() (reference
    train/context.py TrainContext)."""

    def __init__(self, session: "TrainSession"):
        self._s = session

    def get_world_rank(self) -> int:
        return self._s.rank

    def get_world_size(self) -> int:
        return self._s.world_size

    def get_local_rank(self) -> int:
        return self._s.local_rank

    def get_node_rank(self) -> int:
        return self._s.node_rank

    def get_trial_name(self) -> str:
        return self._s.run_name

    def get_experiment_name(self) -> str:
        return self._s.run_name

    def get_storage(self) -> str:
        return self._s.storage_dir


class TrainSession:
    def __init__(self, *, rank: int, world_size: int, local_rank: int,
                 node_rank: int, run_name: str, storage_dir: str,
                 restart_index: int, latest_checkpoint: Optional[Checkpoint],
                 dataset_shards: Optional[dict] = None, group_name: str = "default"):
        self.group_name = group_name
        self.rank = rank
        self.world_size = world_size
        self.local_rank = local_rank
        self.node_rank = node_rank
        self.run_name = run_name
        self.storage_dir = storage_dir
        self.restart_index = restart_index
        self.latest_checkpoint = latest_checkpoint
        self.dataset_shards = dataset_shards or {}
        self.reports: list[dict] = []  # drained by the controller
        self.reports_lock = threading.Lock()
        self.report_seq = 0
        self.finished = False

    # ------------------------------------------------------------- user API
    def report(self, metrics: dict, checkpoint: Optional[Checkpoint] = None):
        """reference session.py:672 — metrics to the controller; checkpoint
        persisted rank-aware (rank 0 owns the canonical copy)."""
        entry: dict[str, Any] = {"metrics": dict(metrics), "rank": self.rank}
        if checkpoint is not None:
            if self.rank == 0:
                self.report_seq += 1
                # Namespaced by restart attempt: a resumed run must never
                # copytree onto an earlier attempt's checkpoint dirs.
                dest = os.path.join(
                    self.storage_dir, "checkpoints",
                    f"checkpoint_r{self.restart_index}_{self.report_seq:06d}")
                os.makedirs(os.path.dirname(dest), exist_ok=True)
                if os.path.abspath(checkpoint.path) != dest:
                    shutil.copytree(checkpoint.path, dest, dirs_exist_ok=True)
                entry["checkpoint_path"] = dest
            else:
                self.report_seq += 1
        with self.reports_lock:
            self.reports.append(entry)

    def get_checkpoint(self) -> Optional[Checkpoint]:
        return self.latest_checkpoint

    def get_dataset_shard(self, name: str = "train"):
        shard = self.dataset_shards.get(name)
        if shard is None:
            raise KeyError(f"no dataset shard named {name!r}; pass datasets= to the trainer")
        return shard

    def drain_reports(self) -> list[dict]:
        with self.reports_lock:
            out = self.reports
            self.reports = []
        return out


def init_session(**kw) -> TrainSession:
    global _session
    with _session_lock:
        _session = TrainSession(**kw)
    return _session


def get_session() -> TrainSession:
    if _session is None:
        raise RuntimeError(
            "No train session in this process — are you inside train_loop_per_worker?")
    return _session


def shutdown_session():
    global _session
    with _session_lock:
        _session = None
