"""JAX helpers for training workers.

The two-level parallelism story (SURVEY §2.4): inside a worker, pjit over
the worker's devices with psum-over-ICI gradients (XLA inserts them from
shardings); across workers, host-tier collective allreduce (DCN role). On a
real multi-host slice, jax.distributed merges the levels into one global
mesh — `global_mesh_from_distributed` is that path.
"""

from __future__ import annotations

import jax
import numpy as np

from ray_tpu.util import collective


def _resolve_group(group_name):
    """None -> the train session's own collective group."""
    if group_name is not None:
        return group_name
    from ray_tpu.train._internal.session import get_session

    return get_session().group_name


def sync_gradients(grads, group_name: str | None = None, average: bool = True):
    """Cross-worker gradient allreduce (host tier, numpy pytrees).
    Plays the role of DDP's NCCL allreduce (reference
    train/torch/config.py DDP wrap); in-worker device grads should already
    be psum'd by the pjit program. group_name=None uses the train
    session's group."""
    group_name = _resolve_group(group_name)
    host_grads = jax.tree_util.tree_map(lambda g: np.asarray(g), grads)
    summed = collective.allreduce(host_grads, group_name=group_name)
    world = collective.get_collective_group_size(group_name)
    if average and world > 1:
        summed = jax.tree_util.tree_map(lambda g: g / world, summed)
    return summed


def sync_metric(value: float, group_name: str | None = None) -> float:
    group_name = _resolve_group(group_name)
    out = collective.allreduce(np.asarray([value], dtype=np.float64),
                               group_name=group_name)
    return float(out[0]) / collective.get_collective_group_size(group_name)


def broadcast_params(params, group_name: str | None = None, src_rank: int = 0):
    """Make rank 0's initial parameters authoritative across the group."""
    group_name = _resolve_group(group_name)
    host = jax.tree_util.tree_map(lambda p: np.asarray(p), params)
    return collective.broadcast(host, src_rank=src_rank, group_name=group_name)


def setup_jax_distributed(group_name: str, rank: int, world_size: int,
                          timeout_s: float = 60.0):
    """Join all train workers into ONE jax process group: rank 0 reserves a
    coordinator port and publishes it through the controller KV; everyone
    calls jax.distributed.initialize. After this, jax.devices() spans every
    worker's chips and global_mesh_from_distributed builds the slice-wide
    mesh (reference role: torch.distributed init_method rendezvous)."""
    import socket
    import time

    from ray_tpu._private.worker import global_worker

    w = global_worker()
    key = f"jaxdist/{group_name}/coordinator"
    if rank == 0:
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
        s.close()  # race-prone in theory; jax rebinds immediately
        # Workers bind loopback in this runtime; on a real multi-host
        # deployment the node agent's host IP takes this seat.
        host = w.server_addr[0] if w.server_addr else "127.0.0.1"
        addr = f"{host}:{port}"
        w.kv("put", ns="train", key=key, value=addr.encode())
    else:
        deadline = time.monotonic() + timeout_s
        addr = None
        while time.monotonic() < deadline:
            v = w.kv("get", ns="train", key=key)["value"]
            if v is not None:
                addr = bytes(v).decode()
                break
            time.sleep(0.05)
        if addr is None:
            raise TimeoutError("jax.distributed coordinator rendezvous timed out")
    jax.distributed.initialize(coordinator_address=addr,
                               num_processes=world_size, process_id=rank)
    return addr


def global_mesh_from_distributed(axis_names=("dp",), shape=None):
    """Multi-host path: after jax.distributed.initialize on every worker,
    build one mesh over ALL processes' devices (reference role:
    torch dist world; TPU-native: one GSPMD program over the slice)."""
    devices = jax.devices()
    if shape is None:
        shape = (len(devices),)
    return jax.sharding.Mesh(np.asarray(devices).reshape(shape), axis_names)
