"""Checkpoints: URI-addressed directories + the async sharded engine.

Parity target: reference python/ray/train/_checkpoint.py (Checkpoint =
directory + filesystem URI) for the `Checkpoint` class, and Orbax-style
async sharded checkpointing (Check-N-Run-style overlapped saves) for the
engine: `save_async(state, dir)` snapshots jax.Arrays device->host
synchronously, then a background writer streams each host's local shards
(pickle5 out-of-band) through the pluggable storage backend
(`ray_tpu/storage/`), and a global MANIFEST.json is written LAST via
atomic rename — the commit point. `restore(dir, shardings=...)` reshards
on load: each host reads only the saved shards overlapping the slices its
NEW sharding needs, so a 4-way save restores onto 2 or 8 workers (elastic
restart after preemption).

Layout of a committed checkpoint dir (flat, any backend):

    a0003_001_r0.bin      array leaf 3, shard 1, written by rank 0
                          (SerializedObject wire layout: pickle5 header +
                          raw out-of-band buffers)
    tree_r0.bin           pickled tree skeleton + non-array leaves (rank 0)
    _wmeta_r{K}.json      rank K's shard metadata + digests (the storage-
                          mediated commit barrier: rank 0 merges these)
    MANIFEST.json         step, per-leaf shape/dtype/sharding, shard->file
                          map, content digests. Present == committed.

Retention (`RT_CKPT_KEEP`) and GC of uncommitted partials run after each
commit; checkpoints pinned via `pin()` (e.g. a PBT clone's restore donor)
survive until every owner unpins.
"""

from __future__ import annotations

import hashlib
import json
import logging
import os
import shutil
import sys
import tempfile
import threading
import time
from concurrent.futures import Future, ThreadPoolExecutor
from contextlib import contextmanager
from typing import Any, Callable, Optional, Union

from ray_tpu import storage
from ray_tpu._private import tracing as _tracing
from ray_tpu.storage import StorageNotFoundError, StorageTransientError

logger = logging.getLogger(__name__)

MANIFEST = "MANIFEST.json"
_FORMAT = 1


# --------------------------------------------------------------------------
# Checkpoint: the directory handle (reference _checkpoint.py), now URI-aware.
# --------------------------------------------------------------------------
class Checkpoint:
    def __init__(self, path: str, metadata: Optional[dict] = None):
        if storage.is_local(path):
            path = os.path.abspath(storage.local_path(path) or path)
        self.path = path
        self._metadata = metadata

    @property
    def uri(self) -> str:
        return self.path

    @classmethod
    def from_directory(cls, path: str) -> "Checkpoint":
        return cls(path)

    def to_directory(self, dest: Optional[str] = None) -> str:
        dest = dest or tempfile.mkdtemp(prefix="rt_ckpt_")
        local = storage.local_path(self.path)
        if local is not None:
            if os.path.abspath(dest) != local:
                shutil.copytree(local, dest, dirs_exist_ok=True)
            return dest
        _materialize(self.path, dest)
        return dest

    @contextmanager
    def as_directory(self):
        local = storage.local_path(self.path)
        if local is not None:
            yield local
            return
        dest = tempfile.mkdtemp(prefix="rt_ckpt_")
        try:
            _materialize(self.path, dest)
            yield dest
        finally:
            shutil.rmtree(dest, ignore_errors=True)

    def get_metadata(self) -> dict:
        if self._metadata is not None:
            return self._metadata
        try:
            return json.loads(
                storage.get_bytes(storage.join(self.path, ".metadata.json")))
        except (StorageNotFoundError, ValueError):
            return {}

    def set_metadata(self, metadata: dict):
        self._metadata = metadata
        storage.put(storage.join(self.path, ".metadata.json"),
                    json.dumps(metadata).encode())

    def __repr__(self):
        return f"Checkpoint({self.path})"

    def __reduce__(self):
        return (Checkpoint, (self.path, self._metadata))


def _materialize(uri: str, dest: str) -> None:
    """Download every object under a (flat or directory-kind) checkpoint
    URI into a local directory."""
    os.makedirs(dest, exist_ok=True)
    man = None
    mpath = storage.join(uri, MANIFEST)
    if storage.exists(mpath):
        man = json.loads(storage.get_bytes(mpath))
    if man and man.get("kind") == "directory":
        names = list(man["files"]) + [MANIFEST]
    else:
        names = storage.listdir(uri)
    for name in names:
        blob = storage.get_bytes(storage.join(uri, name))
        target = os.path.join(dest, name)
        os.makedirs(os.path.dirname(target), exist_ok=True)
        with open(target, "wb") as f:
            f.write(blob)


# --------------------------------------------------------------------------
# Tree walking: dict/list/tuple/namedtuple containers, everything else a
# leaf. Array leaves (jax.Array / np.ndarray) become shard files; other
# leaves ride pickled inside the tree skeleton file.
# --------------------------------------------------------------------------
class _ArrayStub:
    __slots__ = ("index",)

    def __init__(self, index: int):
        self.index = index

    def __reduce__(self):
        return (_ArrayStub, (self.index,))


def _is_jax_array(x) -> bool:
    jax = sys.modules.get("jax")
    return jax is not None and isinstance(x, jax.Array)


def _walk_extract(tree, path: tuple, arrays: list) -> Any:
    """Return a skeleton copy of `tree` with array leaves replaced by
    _ArrayStub markers; appends (path_str, array) to `arrays`."""
    import numpy as np

    if isinstance(tree, dict):
        return {k: _walk_extract(v, path + (str(k),), arrays)
                for k, v in tree.items()}
    if isinstance(tree, (list, tuple)):
        kids = [_walk_extract(v, path + (str(i),), arrays)
                for i, v in enumerate(tree)]
        if isinstance(tree, list):
            return kids
        if hasattr(tree, "_fields"):  # namedtuple (optax states etc.)
            return type(tree)(*kids)
        return tuple(kids)
    if _is_jax_array(tree) or isinstance(tree, np.ndarray):
        arrays.append(("/".join(path) or ".", tree))
        return _ArrayStub(len(arrays) - 1)
    return tree


def _walk_fill(tree, arrays: list) -> Any:
    """Inverse of _walk_extract: replace stubs with restored arrays."""
    if isinstance(tree, _ArrayStub):
        return arrays[tree.index]
    if isinstance(tree, dict):
        return {k: _walk_fill(v, arrays) for k, v in tree.items()}
    if isinstance(tree, list):
        return [_walk_fill(v, arrays) for v in tree]
    if isinstance(tree, tuple):
        kids = [_walk_fill(v, arrays) for v in tree]
        if hasattr(tree, "_fields"):
            return type(tree)(*kids)
        return tuple(kids)
    return tree


def _norm_index(idx, shape) -> list[list[int]]:
    """Normalize a tuple of slices (a shard's position in the global
    array) to [[start, stop], ...] over `shape`."""
    out = []
    for s, dim in zip(idx, shape):
        start = 0 if s.start is None else int(s.start)
        stop = dim if s.stop is None else int(s.stop)
        out.append([start, stop])
    return out


def _snapshot_leaf(path: str, arr) -> dict:
    """Device->host snapshot of one array leaf: a list of host-resident
    shard arrays plus their global indices. On device backends np.asarray
    (host_view) performs the D2H copy here, synchronously. On host
    backends (CPU, TPU host views) it returns a zero-copy VIEW of the
    array's memory — which XLA buffer donation (jit donate_argnums) can
    free/reuse while the background writer is still streaming it, silently
    corrupting the checkpoint. So views that don't own their data are
    copied before save_async returns (RT_CKPT_SNAPSHOT_COPY=0 restores
    zero-copy views for donation-free loops chasing the copy cost)."""
    import numpy as np

    from ray_tpu._private.device_store import host_view
    from ray_tpu._private.rtconfig import CONFIG

    copy_views = CONFIG.ckpt_snapshot_copy

    def snap(a) -> np.ndarray:
        nd = host_view(a)
        if copy_views and not nd.flags["OWNDATA"]:
            nd = nd.copy()
        return nd

    if isinstance(arr, np.ndarray):
        # Mutable host array: copy now — "snapshot" semantics.
        nd = np.array(arr, copy=True)
        return {"path": path, "shape": list(arr.shape),
                "dtype": str(arr.dtype), "sharding": "host",
                "shards": [{"index": _norm_index(
                    tuple(slice(0, d) for d in arr.shape), arr.shape),
                    "data": nd}]}
    shards = []
    for sh in arr.addressable_shards:
        if sh.replica_id != 0:
            continue  # exactly one process writes each global shard
        shards.append({"index": _norm_index(sh.index, arr.shape),
                       "data": snap(sh.data)})
    return {"path": path, "shape": list(arr.shape),
            "dtype": str(arr.dtype), "sharding": repr(arr.sharding),
            "shards": shards}


# --------------------------------------------------------------------------
# Retry: transient storage failures back off and retry (sim:// chaos, real
# network blips). Fatal StorageErrors propagate immediately.
# --------------------------------------------------------------------------
def _retried(fn: Callable, what: str, stats: Optional[dict] = None):
    from ray_tpu._private.rtconfig import CONFIG

    attempts = max(1, int(CONFIG.ckpt_retries) + 1)
    delay = CONFIG.ckpt_retry_base_s
    for i in range(attempts):
        try:
            return fn()
        except StorageTransientError:
            if stats is not None:
                stats["retries"] = stats.get("retries", 0) + 1
            if i == attempts - 1:
                raise
            logger.warning("checkpoint: transient storage failure on %s "
                           "(attempt %d/%d), backing off %.2fs",
                           what, i + 1, attempts, delay)
            time.sleep(delay)
            delay *= 2


def _blob_parts(value) -> tuple[list, int, str]:
    """pickle5-oob parts for one payload, with total size and sha1."""
    from ray_tpu._private.serialization import SerializedObject, dumps_oob

    header, buffers = dumps_oob(value)
    parts = SerializedObject(header=header, buffers=buffers,
                             contained_refs=[]).to_parts()
    h = hashlib.sha1()
    n = 0
    for p in parts:
        h.update(p)
        n += len(p)
    return parts, n, h.hexdigest()


def _load_blob(blob: bytes):
    from ray_tpu._private.serialization import SerializedObject, loads_oob

    sobj = SerializedObject.from_buffer(blob)
    return loads_oob(sobj.header, list(sobj.buffers))


# --------------------------------------------------------------------------
# Save
# --------------------------------------------------------------------------
_writer_lock = threading.Lock()
_writer: Optional[ThreadPoolExecutor] = None


def _writer_pool() -> ThreadPoolExecutor:
    """ONE background writer per process: saves commit in FIFO order, so a
    later checkpoint can never become visible before an earlier one."""
    global _writer
    with _writer_lock:
        if _writer is None:
            _writer = ThreadPoolExecutor(
                max_workers=1, thread_name_prefix="rt-ckpt-writer")
        return _writer


class SaveHandle:
    """Future for an in-flight (or completed) save. `result()` returns the
    commit info dict; raises if the save failed. `stats` counts retries."""

    def __init__(self, uri: str, step, rank: int, fut: Future, stats: dict):
        self.uri = uri
        self.step = step
        self.rank = rank
        self._fut = fut
        self.stats = stats

    def result(self, timeout: Optional[float] = None) -> dict:
        return self._fut.result(timeout)

    def done(self) -> bool:
        return self._fut.done()

    def exception(self, timeout: Optional[float] = None):
        return self._fut.exception(timeout)


def save_async(state, dir_uri: str, *, step=None, rank: int = 0,
               world_size: int = 1) -> SaveHandle:
    """Snapshot `state` (device->host, synchronous) and write it to
    `dir_uri` off the caller's path. Every rank of a multi-host save calls
    this with the SAME dir; each writes only its local shards and rank 0
    commits the manifest once all ranks' metadata has landed in storage.
    With RT_CKPT_ASYNC=0 the write+commit run inline (byte-identical
    output), and result() is already resolved on return."""
    from ray_tpu._private.rtconfig import CONFIG

    arrays: list = []
    # Stage 1 of the traced save: the synchronous device->host snapshot
    # (the only part on the caller's step path when async).
    with _tracing.span("ckpt.snapshot", "ckpt",
                       {"step": step, "rank": rank}):
        skeleton = _walk_extract(state, (), arrays)
        plan = {
            "kind": "state",
            "dir": dir_uri,
            "step": step,
            "rank": rank,
            "world": world_size,
            "leaves": [_snapshot_leaf(p, a) for p, a in arrays],
            "skeleton": skeleton if rank == 0 else None,
            "start": time.time(),
        }
    # The writer thread carries no contextvar: hand it the caller's trace
    # context so write/commit stages land in the same trace.
    plan["trace"] = _tracing.current() if _tracing.enabled() else None
    stats: dict = {}
    if CONFIG.ckpt_async:
        fut = _writer_pool().submit(_write_plan, plan, stats)
    else:
        fut = Future()
        try:
            fut.set_result(_write_plan(plan, stats))
        except BaseException as e:
            fut.set_exception(e)
    return SaveHandle(dir_uri, step, rank, fut, stats)


def save(state, dir_uri: str, *, step=None, rank: int = 0,
         world_size: int = 1) -> dict:
    """Synchronous save: blocks until committed (rank 0) / durable
    (other ranks). Same bytes as save_async."""
    plan_stats: dict = {}
    arrays: list = []
    with _tracing.span("ckpt.snapshot", "ckpt",
                       {"step": step, "rank": rank}):
        skeleton = _walk_extract(state, (), arrays)
        plan = {
            "kind": "state", "dir": dir_uri, "step": step, "rank": rank,
            "world": world_size,
            "leaves": [_snapshot_leaf(p, a) for p, a in arrays],
            "skeleton": skeleton if rank == 0 else None,
            "start": time.time(),
        }
    plan["trace"] = _tracing.current() if _tracing.enabled() else None
    return _write_plan(plan, plan_stats)


def upload_directory_async(src_dir: str, dest_uri: str, *,
                           step=None) -> SaveHandle:
    """Directory checkpoint through the same seam: file contents are
    buffered in RAM synchronously (the source is often a TemporaryDirectory
    deleted right after report()), then streamed + manifest-committed in
    the background."""
    from ray_tpu._private.rtconfig import CONFIG

    files: dict[str, bytes] = {}
    src_dir = os.path.abspath(src_dir)
    for root, _dirs, names in os.walk(src_dir):
        for name in names:
            full = os.path.join(root, name)
            rel = os.path.relpath(full, src_dir).replace(os.sep, "/")
            with open(full, "rb") as f:
                files[rel] = f.read()
    plan = {"kind": "directory", "dir": dest_uri, "step": step,
            "rank": 0, "world": 1, "files": files, "start": time.time()}
    plan["trace"] = _tracing.current() if _tracing.enabled() else None
    stats: dict = {}
    if CONFIG.ckpt_async:
        fut = _writer_pool().submit(_write_plan, plan, stats)
    else:
        fut = Future()
        try:
            fut.set_result(_write_plan(plan, stats))
        except BaseException as e:
            fut.set_exception(e)
    return SaveHandle(dest_uri, step, 0, fut, stats)


def upload_directory(src_dir: str, dest_uri: str, *, step=None) -> dict:
    h = upload_directory_async(src_dir, dest_uri, step=step)
    return h.result()


def _write_plan(plan: dict, stats: dict) -> dict:
    """The background half of a save: stream files through the backend
    (with transient-failure retry), land per-rank metadata, and — on the
    committing rank — merge + write MANIFEST.json last, then run
    retention/GC and mint metrics."""
    t0 = time.perf_counter()
    tctx = plan.get("trace")
    t_write = time.time()
    d = plan["dir"]
    rank, world = plan["rank"], plan["world"]
    marker = storage.join(d, f"_inprogress_r{rank}")
    _retried(lambda: storage.put(marker, json.dumps(
        {"start": plan["start"], "rank": rank, "world": world}).encode()),
        marker, stats)

    total = 0
    if plan["kind"] == "directory":
        files_meta: dict[str, dict] = {}
        for rel, blob in plan["files"].items():
            h = hashlib.sha1(blob).hexdigest()
            uri = storage.join(d, rel)
            _retried(lambda u=uri, b=blob: storage.put(u, b), uri, stats)
            files_meta[rel] = {"bytes": len(blob), "sha1": h}
            total += len(blob)
        manifest = {"format": _FORMAT, "kind": "directory",
                    "step": plan["step"], "created": time.time(),
                    "world_size": 1, "files": files_meta, "bytes": total}
        _tracing.record_span_in(tctx, "ckpt.write", "ckpt", t_write,
                                time.time(),
                                {"step": plan["step"], "bytes": total})
        t_c = time.time()
        _commit(d, rank, manifest, t0, stats)
        _tracing.record_span_in(tctx, "ckpt.commit", "ckpt", t_c,
                                time.time(), {"step": plan["step"]})
        return manifest

    # ---- state checkpoint: shard files + tree + wmeta ---------------------
    leaves_meta: dict[str, dict] = {}
    for li, leaf in enumerate(plan["leaves"]):
        shard_meta = []
        # Host numpy leaves are replicated by convention: rank 0 writes the
        # canonical copy, other ranks contribute metadata only (the merge
        # would dedup identical coverage anyway — this skips the upload).
        shards = leaf["shards"] if (rank == 0 or leaf["sharding"] != "host") \
            else []
        for si, sh in enumerate(shards):
            fname = f"a{li:04d}_{si:03d}_r{rank}.bin"
            parts, nbytes, digest = _blob_parts(sh["data"])
            uri = storage.join(d, fname)
            _retried(lambda u=uri, p=parts: storage.put(u, p), uri, stats)
            shard_meta.append({"file": fname, "index": sh["index"],
                               "bytes": nbytes, "sha1": digest,
                               "rank": rank})
            total += nbytes
        leaves_meta[str(li)] = {"path": leaf["path"], "shape": leaf["shape"],
                                "dtype": leaf["dtype"],
                                "sharding": leaf["sharding"],
                                "shards": shard_meta}
    wmeta: dict[str, Any] = {"rank": rank, "world": world,
                             "leaves": leaves_meta, "bytes": total}
    if rank == 0:
        tree_file = "tree_r0.bin"
        parts, nbytes, digest = _blob_parts(plan["skeleton"])
        _retried(lambda: storage.put(storage.join(d, tree_file), parts),
                 tree_file, stats)
        total += nbytes
        wmeta["bytes"] = total
        wmeta["tree_file"] = tree_file
        wmeta["tree_sha1"] = digest
        wmeta["tree_bytes"] = nbytes
    wmeta_uri = storage.join(d, f"_wmeta_r{rank}.json")
    _retried(lambda: storage.put(wmeta_uri, json.dumps(wmeta).encode()),
             wmeta_uri, stats)
    _tracing.record_span_in(tctx, "ckpt.write", "ckpt", t_write, time.time(),
                            {"step": plan["step"], "rank": rank,
                             "bytes": total})

    if rank != 0:
        # This rank's shards are durable; rank 0 owns the commit.
        try:
            storage.delete(marker)
        except Exception:
            pass
        return wmeta

    manifest = _merge_and_commit(plan, wmeta, t0, stats)
    return manifest


def _merge_and_commit(plan: dict, wmeta0: dict, t0: float,
                      stats: dict) -> dict:
    """Rank 0: wait (via storage, not RPC) for every rank's wmeta, merge
    shard maps, write the manifest LAST via atomic rename."""
    from ray_tpu._private.rtconfig import CONFIG

    d = plan["dir"]
    world = plan["world"]
    metas = {0: wmeta0}
    deadline = time.monotonic() + CONFIG.ckpt_commit_timeout_s
    for r in range(1, world):
        uri = storage.join(d, f"_wmeta_r{r}.json")
        while True:
            if storage.exists(uri):
                metas[r] = json.loads(storage.get_bytes(uri))
                break
            if time.monotonic() > deadline:
                raise TimeoutError(
                    f"checkpoint commit: rank {r}'s shard metadata never "
                    f"appeared in {d} (worker died mid-save?); not "
                    f"committing — the partial will be GC'd")
            time.sleep(0.05)

    leaves: list[dict] = []
    total = 0
    li = 0
    while str(li) in wmeta0["leaves"]:
        base = dict(wmeta0["leaves"][str(li)])
        shards: list[dict] = []
        seen = set()
        for r in sorted(metas):
            for sh in metas[r]["leaves"].get(str(li), {}).get("shards", []):
                key = json.dumps(sh["index"])
                if key in seen:
                    continue  # defensively drop duplicate coverage
                seen.add(key)
                shards.append(sh)
        base["shards"] = shards
        leaves.append(base)
        li += 1
    for r, m in metas.items():
        total += m.get("bytes", 0)
    manifest = {"format": _FORMAT, "kind": "state", "step": plan["step"],
                "created": time.time(), "world_size": world,
                "tree_file": wmeta0.get("tree_file"),
                "tree_sha1": wmeta0.get("tree_sha1"),
                "leaves": leaves, "bytes": total}
    t_c = time.time()
    _commit(d, 0, manifest, t0, stats)
    _tracing.record_span_in(plan.get("trace"), "ckpt.commit", "ckpt", t_c,
                            time.time(),
                            {"step": plan["step"], "world": world})
    return manifest


def _commit(d: str, rank: int, manifest: dict, t0: float,
            stats: dict) -> None:
    from ray_tpu._private.rtconfig import CONFIG

    tmp = storage.join(d, MANIFEST + ".tmp")
    _retried(lambda: storage.put(tmp, json.dumps(manifest).encode()),
             tmp, stats)
    _retried(lambda: storage.rename(tmp, storage.join(d, MANIFEST)),
             MANIFEST, stats)
    for r in range(manifest.get("world_size", 1)):
        try:
            storage.delete(storage.join(d, f"_inprogress_r{r}"))
        except Exception:
            pass
    elapsed = time.perf_counter() - t0
    stats["commit_s"] = elapsed
    _mint_metrics(manifest, elapsed)
    _register_with_controller(d, manifest)
    from ray_tpu._private.events import emit_event

    try:
        emit_event("checkpoint_commit",
                   f"checkpoint committed at {d} (step "
                   f"{manifest.get('step')}, {manifest.get('bytes')} bytes)",
                   entity=(d,),
                   attrs={"step": manifest.get("step"),
                          "bytes": manifest.get("bytes"),
                          "commit_s": round(elapsed, 3)})
    except Exception:
        pass
    parent = storage.parent(d)
    keep = CONFIG.ckpt_keep
    if keep:
        try:
            deleted = retention(parent, keep)
            if deleted:
                emit_event("checkpoint_gc",
                           f"retention deleted {len(deleted)} checkpoint(s) "
                           f"under {parent} (keep-last-{keep})",
                           entity=(parent,),
                           attrs={"deleted": len(deleted)})
        except Exception:
            logger.exception("checkpoint retention failed under %s", parent)
    try:
        gc_partials(parent)
    except Exception:
        logger.exception("checkpoint partial-GC failed under %s", parent)


def _mint_metrics(manifest: dict, elapsed: float) -> None:
    try:
        from ray_tpu._private.rtconfig import CONFIG
        from ray_tpu.util import metrics as _m

        mode = "async" if CONFIG.ckpt_async else "sync"
        _m.CHECKPOINT_SAVE_SECONDS.observe(elapsed, tags={"mode": mode})
        if manifest.get("bytes"):
            _m.CHECKPOINT_BYTES.inc(manifest["bytes"])
        _m.CHECKPOINT_COMMITTED.inc()
    except Exception:
        pass


def _register_with_controller(uri: str, manifest: dict) -> None:
    """Best-effort observability row: committed checkpoints show up in
    `util.state.list_checkpoints()` and the CLI via the controller KV."""
    try:
        from ray_tpu._private.worker import global_worker

        w = global_worker()
        if w is None or getattr(w, "_shutdown", False):
            return
        info = {"uri": uri, "step": manifest.get("step"),
                "kind": manifest.get("kind"),
                "bytes": manifest.get("bytes"),
                "world_size": manifest.get("world_size"),
                "created": manifest.get("created")}
        w.kv("put", ns="_checkpoints", key=uri,
             value=json.dumps(info).encode())
    except Exception:
        pass


# --------------------------------------------------------------------------
# Restore (with resharding)
# --------------------------------------------------------------------------
ShardingsArg = Union[None, dict, Callable, Any]


def restore(dir_uri: str, *, mesh=None, shardings: ShardingsArg = None,
            verify: bool = True):
    """Load a committed state checkpoint. `shardings` picks the NEW layout:

      - None: every array leaf comes back as a host numpy array (fully
        assembled from its saved shards).
      - a single jax Sharding (or PartitionSpec with `mesh`): applied to
        every array leaf.
      - dict {leaf_path: Sharding/PartitionSpec/None}: per-leaf; missing
        or None entries assemble to host numpy.
      - callable (path, shape, dtype) -> Sharding/None.

    Each host materializes ONLY the saved shards overlapping the slices
    its new sharding makes addressable here — the resharding-on-load that
    lets a 4-way save restore onto 2 or 8 hosts."""
    man = load_manifest(dir_uri)
    if man is None:
        raise StorageNotFoundError(
            f"no committed checkpoint at {dir_uri} (MANIFEST.json missing)")
    if man.get("kind") != "state":
        raise ValueError(
            f"{dir_uri} is a {man.get('kind')!r} checkpoint; use "
            f"Checkpoint(...).as_directory() for directory checkpoints")
    tree_blob = storage.get_bytes(storage.join(dir_uri, man["tree_file"]))
    if verify and man.get("tree_sha1"):
        if hashlib.sha1(tree_blob).hexdigest() != man["tree_sha1"]:
            raise storage.StorageError(
                f"checkpoint {dir_uri}: tree file digest mismatch")
    skeleton = _load_blob(tree_blob)
    arrays = []
    for leaf in man["leaves"]:
        sh = _sharding_for(shardings, mesh, leaf)
        arrays.append(_restore_leaf(dir_uri, leaf, sh, verify))
    return _walk_fill(skeleton, arrays)


def _sharding_for(shardings: ShardingsArg, mesh, leaf: dict):
    val = shardings
    if isinstance(shardings, dict):
        val = shardings.get(leaf["path"])
    elif callable(shardings) and not _is_sharding(shardings):
        val = shardings(leaf["path"], tuple(leaf["shape"]), leaf["dtype"])
    if val is None:
        return None
    if mesh is not None and not _is_sharding(val):
        from jax.sharding import NamedSharding

        return NamedSharding(mesh, val)  # val is a PartitionSpec
    return val


def _is_sharding(x) -> bool:
    jax = sys.modules.get("jax")
    return jax is not None and isinstance(x, jax.sharding.Sharding)


def _restore_leaf(dir_uri: str, leaf: dict, sharding, verify: bool):
    import numpy as np

    shape = tuple(leaf["shape"])
    dtype = np.dtype(leaf["dtype"])
    cache: dict[str, Any] = {}

    def load(sh: dict):
        if sh["file"] not in cache:
            blob = storage.get_bytes(storage.join(dir_uri, sh["file"]))
            if verify and hashlib.sha1(blob).hexdigest() != sh["sha1"]:
                raise storage.StorageError(
                    f"checkpoint {dir_uri}: shard {sh['file']} digest "
                    f"mismatch (corrupt or truncated)")
            cache[sh["file"]] = _load_blob(blob)
        return cache[sh["file"]]

    if sharding is None:
        out = np.empty(shape, dtype)
        for sh in leaf["shards"]:
            sl = tuple(slice(a, b) for a, b in sh["index"])
            out[sl] = load(sh)
        return out

    import jax

    idx_map = sharding.addressable_devices_indices_map(shape)
    per_dev = []
    devs = []
    for dev, idx in idx_map.items():
        tgt = _norm_index(idx, shape)
        buf = np.empty([b - a for a, b in tgt], dtype)
        for sh in leaf["shards"]:
            inter = _intersect(tgt, sh["index"])
            if inter is None:
                continue
            tgt_sl, src_sl = inter
            buf[tgt_sl] = load(sh)[src_sl]
        per_dev.append(jax.device_put(buf.reshape(
            [b - a for a, b in tgt]), dev))
        devs.append(dev)
    return jax.make_array_from_single_device_arrays(shape, sharding, per_dev)


def _intersect(tgt: list, src: list):
    """Overlap of two [[start, stop], ...] boxes: (target-local slices,
    source-local slices), or None when disjoint."""
    tgt_sl, src_sl = [], []
    for (ts, te), (ss, se) in zip(tgt, src):
        lo, hi = max(ts, ss), min(te, se)
        if hi <= lo:
            return None
        tgt_sl.append(slice(lo - ts, hi - ts))
        src_sl.append(slice(lo - ss, hi - ss))
    return tuple(tgt_sl), tuple(src_sl)


# --------------------------------------------------------------------------
# Listing / retention / pins / GC
# --------------------------------------------------------------------------
def load_manifest(dir_uri: str) -> Optional[dict]:
    try:
        return json.loads(storage.get_bytes(storage.join(dir_uri, MANIFEST)))
    except (StorageNotFoundError, ValueError):
        return None


def list_checkpoints(parent_uri: str) -> list[dict]:
    """Rows for every checkpoint dir under `parent_uri`: committed ones
    carry manifest fields; uncommitted partials are flagged."""
    rows = []
    for name in storage.listdir(parent_uri):
        if name.endswith(".refs") or name == MANIFEST:
            continue
        d = storage.join(parent_uri, name)
        man = load_manifest(d)
        if man is not None:
            rows.append({"uri": d, "name": name, "committed": True,
                         "step": man.get("step"), "kind": man.get("kind"),
                         "bytes": man.get("bytes"),
                         "world_size": man.get("world_size"),
                         "created": man.get("created"),
                         "pins": pins(d)})
        elif any(n.startswith("_inprogress_r")
                 for n in storage.listdir(d)):
            rows.append({"uri": d, "name": name, "committed": False,
                         "step": None, "kind": None, "bytes": None,
                         "world_size": None, "created": None,
                         "pins": pins(d)})
    # Order by COMMIT TIME, not step: the train session's step counter
    # resets on every restart attempt, so a post-restart checkpoint (step
    # 1) is newer than the pre-crash step 3 — retention and
    # latest_checkpoint must see it that way or keep-last-K would delete
    # the run's actual latest checkpoint.
    rows.sort(key=lambda r: (r["created"] is None,  # partials last
                             r["created"] or 0, r["name"]))
    return rows


def latest_checkpoint(parent_uri: str) -> Optional[str]:
    committed = [r for r in list_checkpoints(parent_uri) if r["committed"]]
    return committed[-1]["uri"] if committed else None


def pin(ckpt_uri: str, owner: str) -> None:
    """Refcount a checkpoint dir: it survives retention/GC until every
    owner unpins (the PBT clone-from-donor hazard fix — marker files on
    the shared backend, visible across processes)."""
    storage.put(storage.join(ckpt_uri + ".refs", owner), b"1")


def unpin(ckpt_uri: str, owner: str) -> None:
    try:
        storage.delete(storage.join(ckpt_uri + ".refs", owner))
    except Exception:
        pass


def pins(ckpt_uri: str) -> list[str]:
    try:
        return storage.listdir(ckpt_uri + ".refs")
    except Exception:
        return []


def delete_checkpoint(ckpt_uri: str, *, force: bool = False) -> bool:
    """Remove a checkpoint dir unless pinned (force overrides)."""
    if not force and pins(ckpt_uri):
        return False
    storage.delete_prefix(ckpt_uri)
    storage.delete_prefix(ckpt_uri + ".refs")
    return True


def retention(parent_uri: str, keep: int) -> list[str]:
    """Keep the newest `keep` committed checkpoints under `parent_uri`;
    delete the rest except pinned ones. Returns deleted URIs."""
    if not keep or keep <= 0:
        return []
    committed = [r for r in list_checkpoints(parent_uri) if r["committed"]]
    deleted = []
    for row in committed[:-keep]:
        if delete_checkpoint(row["uri"]):
            deleted.append(row["uri"])
    return deleted


def gc_partials(parent_uri: str, grace_s: Optional[float] = None) -> list[str]:
    """Collect uncommitted checkpoint dirs (in-progress markers, no
    manifest) older than the grace window — the debris of a worker killed
    or a backend severed mid-save."""
    from ray_tpu._private.rtconfig import CONFIG

    if grace_s is None:
        grace_s = CONFIG.ckpt_partial_grace_s
    now = time.time()
    deleted = []
    for name in storage.listdir(parent_uri):
        if name.endswith(".refs") or name == MANIFEST:
            continue
        d = storage.join(parent_uri, name)
        names = storage.listdir(d)
        markers = [n for n in names if n.startswith("_inprogress_r")]
        if not markers or MANIFEST in names:
            continue
        newest = 0.0
        for m in markers:
            try:
                newest = max(newest, json.loads(
                    storage.get_bytes(storage.join(d, m)))["start"])
            except Exception:
                pass
        if now - newest > grace_s:
            if delete_checkpoint(d):
                deleted.append(d)
    return deleted
