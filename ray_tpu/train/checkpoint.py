"""Directory checkpoints.

Parity target: reference python/ray/train/_checkpoint.py:56 (Checkpoint =
directory + filesystem URI; as_directory/from_directory/to_directory).
Local filesystems only in this round; the URI seam is where GCS/S3 mounts
via a filesystem adapter.
"""

from __future__ import annotations

import json
import os
import shutil
import tempfile
from contextlib import contextmanager
from typing import Optional


class Checkpoint:
    def __init__(self, path: str, metadata: Optional[dict] = None):
        self.path = os.path.abspath(path)
        self._metadata = metadata

    @classmethod
    def from_directory(cls, path: str) -> "Checkpoint":
        return cls(path)

    def to_directory(self, dest: Optional[str] = None) -> str:
        dest = dest or tempfile.mkdtemp(prefix="rt_ckpt_")
        if os.path.abspath(dest) != self.path:
            shutil.copytree(self.path, dest, dirs_exist_ok=True)
        return dest

    @contextmanager
    def as_directory(self):
        yield self.path

    def get_metadata(self) -> dict:
        if self._metadata is not None:
            return self._metadata
        meta_file = os.path.join(self.path, ".metadata.json")
        if os.path.exists(meta_file):
            with open(meta_file) as f:
                return json.load(f)
        return {}

    def set_metadata(self, metadata: dict):
        self._metadata = metadata
        with open(os.path.join(self.path, ".metadata.json"), "w") as f:
            json.dump(metadata, f)

    def __repr__(self):
        return f"Checkpoint({self.path})"

    def __reduce__(self):
        return (Checkpoint, (self.path, self._metadata))
