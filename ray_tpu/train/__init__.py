"""ray_tpu.train — distributed training on the cluster runtime.

Parity target: reference python/ray/train (JaxTrainer plays
DataParallelTrainer/TorchTrainer, base_trainer.py:651 fit; the v2
controller loop; session report/get_checkpoint/get_dataset_shard;
worker_group actor fleet).

TPU-native design: a training worker == one host process of a multi-host
mesh. Inside each worker, computation is pjit over that host's devices
(grads psum'd over ICI by XLA). Across workers, gradient/metric sync rides
the host-tier collective group the session joins at startup (the role NCCL
process groups play in the reference, train/torch/config.py:66) — or, on a
real multi-host TPU slice, jax.distributed forms one global mesh and the
cross-host collectives also ride ICI/DCN inside the compiled program.
"""

from ray_tpu.train.checkpoint import Checkpoint
from ray_tpu.train.config import (
    CheckpointConfig,
    FailureConfig,
    RunConfig,
    ScalingConfig,
)
from ray_tpu.train._internal.controller import Result, TrainController
from ray_tpu.train._internal.session import TrainContext, get_session


def report(metrics: dict, checkpoint: Checkpoint | None = None):
    """Report metrics (+ optional checkpoint) from inside
    train_loop_per_worker (reference train/_internal/session.py:672)."""
    get_session().report(metrics, checkpoint)


def get_context() -> TrainContext:
    return TrainContext(get_session())


def get_checkpoint() -> Checkpoint | None:
    return get_session().get_checkpoint()


def get_dataset_shard(name: str = "train"):
    return get_session().get_dataset_shard(name)


class JaxTrainer:
    """Data-parallel (and beyond — the mesh config is the worker's choice)
    trainer over a worker group of actors.

    reference equivalents: DataParallelTrainer (data_parallel_trainer.py:26)
    + TorchTrainer; `.fit()` = base_trainer.py:651.
    """

    def __init__(self, train_loop_per_worker, *, train_loop_config=None,
                 scaling_config: ScalingConfig | None = None,
                 run_config: RunConfig | None = None,
                 datasets: dict | None = None):
        self._train_fn = train_loop_per_worker
        self._config = train_loop_config
        self._scaling = scaling_config or ScalingConfig()
        self._run_config = run_config or RunConfig()
        self._datasets = datasets
        self._controller: TrainController | None = None

    def fit(self) -> Result:
        controller = TrainController(
            train_fn=self._train_fn,
            train_loop_config=self._config,
            scaling_config=self._scaling,
            run_config=self._run_config,
            datasets=self._datasets,
        )
        self._controller = controller
        return controller.run()


# Alias for API parity with the reference's generic trainer name.
DataParallelTrainer = JaxTrainer

__all__ = [
    "JaxTrainer",
    "DataParallelTrainer",
    "ScalingConfig",
    "RunConfig",
    "FailureConfig",
    "CheckpointConfig",
    "Checkpoint",
    "Result",
    "TrainController",
    "report",
    "get_context",
    "get_checkpoint",
    "get_dataset_shard",
]
