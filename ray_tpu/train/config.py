"""Train/AIR configuration dataclasses.

Parity target: reference python/ray/air/config.py (ScalingConfig,
RunConfig, FailureConfig, CheckpointConfig) and ray/train usage of them.
TPU-native deltas: `use_tpu` + `topology` replace `use_gpu`; resources are
expressed in the scheduler's TPU-first resource model.
"""

from __future__ import annotations

import os
from dataclasses import dataclass, field
from typing import Optional


@dataclass
class ScalingConfig:
    """How many training workers and what each one needs
    (reference air/config.py ScalingConfig)."""

    num_workers: int = 1
    use_tpu: bool = False
    resources_per_worker: Optional[dict] = None
    #: TPU slice topology hint, e.g. "v5e-8" (scheduling label; reference
    #: TPUAcceleratorManager pod awareness, accelerators/tpu.py:312).
    topology: Optional[str] = None
    #: Form ONE global jax mesh across all workers via
    #: jax.distributed.initialize (rank 0 hosts the coordinator; the
    #: address rendezvous rides the controller KV). On a real multi-host
    #: TPU slice this is how the per-host processes become one GSPMD
    #: program over ICI/DCN.
    jax_distributed: bool = False
    #: Extra env vars for worker processes, applied BEFORE any import in
    #: the worker (e.g. XLA_FLAGS=--xla_force_host_platform_device_count=4
    #: to give each worker a virtual device mesh in tests).
    worker_env: Optional[dict] = None
    #: Elastic lower bound (reference train v2 ScalingPolicy): on a group
    #: failure the restart sizes itself to what the cluster can actually
    #: place — min_workers..num_workers — instead of waiting forever for
    #: the full quorum (training resumes from the checkpoint with data
    #: re-split over the surviving workers). None = fixed-size restarts.
    min_workers: Optional[int] = None

    def worker_resources(self) -> dict:
        if self.resources_per_worker is not None:
            return dict(self.resources_per_worker)
        if self.use_tpu:
            return {"CPU": 1, "TPU": 1}
        return {"CPU": 1}


@dataclass
class FailureConfig:
    """Elastic-recovery policy (reference air FailureConfig + train v2
    FailurePolicy, failure_handling/failure_policy.py:14): on worker/node
    failure the whole group restarts from the latest checkpoint."""

    max_failures: int = 0  # 0 = fail fast; -1 = unlimited restarts
    #: Group-stall policy (README "Stall detection & watchdogs"): a group
    #: that commits NO progress (no report() drained from any worker) for
    #: this long is treated as a group FAILURE — killed and restarted from
    #: the latest committed checkpoint through the same elastic path as a
    #: crash. Closes the silent-hang gap (a rank wedged in a collective
    #: stops the whole group from reporting, but nothing crashes). None =
    #: disabled.
    stall_timeout_s: Optional[float] = None


@dataclass
class CheckpointConfig:
    """num_to_keep: prune all but the N most recent checkpoints (enforced by
    the controller as reports arrive). checkpoint_frequency is accepted for
    reference-API compatibility but NOT honored — checkpointing cadence is
    whatever the user's train loop reports (a warning is logged if set)."""

    num_to_keep: Optional[int] = None
    checkpoint_frequency: int = 0

    def __post_init__(self):
        if self.checkpoint_frequency:
            import logging

            logging.getLogger(__name__).warning(
                "CheckpointConfig.checkpoint_frequency is not honored; "
                "checkpoint from your train loop via train.report(checkpoint=...)")


@dataclass
class RunConfig:
    name: Optional[str] = None
    storage_path: Optional[str] = None
    failure_config: FailureConfig = field(default_factory=FailureConfig)
    checkpoint_config: CheckpointConfig = field(default_factory=CheckpointConfig)
    #: Tune stop criteria: {"metric": threshold} — a trial stops once any
    #: reported metric reaches its threshold (reference air.RunConfig stop).
    stop: Optional[dict] = None

    def resolved_storage(self) -> str:
        return self.storage_path or os.path.join(
            os.path.expanduser("~"), "ray_tpu_results")
