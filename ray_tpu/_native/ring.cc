// Native core for ray_tpu: futex-backed SPSC ring ops + parallel memcpy.
//
// Parity rationale: the reference implements its low-latency substrate in
// C++ (src/ray/core_worker/experimental_mutable_object_manager.h for
// compiled-graph channels; plasma/object copies in src/ray/object_manager).
// This file is the TPU-native equivalent: the channel header lives in a
// shared-memory segment and both ends block in the kernel (futex) instead
// of burning the (often single) host core on sleep-poll loops.
//
// Header layout at the base of every channel segment (64 bytes, see
// ray_tpu/experimental/channel.py which shares it):
//   [0]  u64 seq    — number of messages ever published by the writer
//   [8]  u64 ack    — number of messages ever consumed by the reader
//   [16] u64 size   — payload byte length of the current message
//   [24] u32 wseq   — futex word mirroring (u32)seq: readers wait on it
//   [28] u32 wack   — futex word mirroring (u32)ack: writers wait on it
//   [32..64) reserved
// Data area starts at byte 64.
//
// Waits are BOUNDED (default 2 ms per kernel wait, then re-check) so a
// peer running the pure-Python fallback — which never calls futex_wake —
// still interoperates; the wake call just makes the native<->native pair
// fast. All functions return 0/length on success, -1 on timeout.

#include <atomic>
#include <cerrno>
#include <cstdint>
#include <cstring>
#include <ctime>
#include <thread>
#include <vector>

#include <linux/futex.h>
#include <sys/syscall.h>
#include <unistd.h>

namespace {

constexpr size_t kHdr = 64;
constexpr long kSliceNs = 2'000'000;  // bounded kernel wait per iteration

struct Hdr {
  std::atomic<uint64_t> seq;
  std::atomic<uint64_t> ack;
  std::atomic<uint64_t> size;
  std::atomic<uint32_t> wseq;
  std::atomic<uint32_t> wack;
};

static_assert(sizeof(Hdr) <= kHdr, "header overflow");

inline Hdr* hdr(uint8_t* base) { return reinterpret_cast<Hdr*>(base); }

inline int futex_wait(std::atomic<uint32_t>* addr, uint32_t expect, long ns) {
  timespec ts{0, ns};
  return syscall(SYS_futex, reinterpret_cast<uint32_t*>(addr),
                 FUTEX_WAIT, expect, &ts, nullptr, 0);
}

inline void futex_wake(std::atomic<uint32_t>* addr) {
  syscall(SYS_futex, reinterpret_cast<uint32_t*>(addr), FUTEX_WAKE,
          INT32_MAX, nullptr, nullptr, 0);
}

inline int64_t now_ns() {
  timespec ts;
  clock_gettime(CLOCK_MONOTONIC, &ts);
  return int64_t(ts.tv_sec) * 1'000'000'000 + ts.tv_nsec;
}

}  // namespace

extern "C" {

// Write one message. Blocks until the previous message is acked (capacity-1
// backpressure, matching the reference mutable-object semantics).
int rt_ring_write(uint8_t* base, uint64_t cap, const uint8_t* data,
                  uint64_t n, int64_t timeout_ns) {
  if (n > cap) return -2;
  Hdr* h = hdr(base);
  const uint64_t seq = h->seq.load(std::memory_order_acquire);
  const int64_t deadline = timeout_ns < 0 ? -1 : now_ns() + timeout_ns;
  while (h->ack.load(std::memory_order_acquire) < seq) {
    if (deadline >= 0 && now_ns() > deadline) return -1;
    futex_wait(&h->wack, static_cast<uint32_t>(seq - 1), kSliceNs);
  }
  std::memcpy(base + kHdr, data, n);
  h->size.store(n, std::memory_order_release);
  h->seq.store(seq + 1, std::memory_order_release);
  h->wseq.store(static_cast<uint32_t>(seq + 1), std::memory_order_release);
  futex_wake(&h->wseq);
  return 0;
}

// Wait until seq > last_read; returns the payload length (copied into out,
// which must hold cap bytes), or -1 on timeout.
int64_t rt_ring_read(uint8_t* base, uint64_t cap, uint8_t* out,
                     uint64_t last_read, int64_t timeout_ns) {
  Hdr* h = hdr(base);
  const int64_t deadline = timeout_ns < 0 ? -1 : now_ns() + timeout_ns;
  while (h->seq.load(std::memory_order_acquire) <= last_read) {
    if (deadline >= 0 && now_ns() > deadline) return -1;
    futex_wait(&h->wseq, static_cast<uint32_t>(last_read), kSliceNs);
  }
  const uint64_t n = h->size.load(std::memory_order_acquire);
  if (n > cap) return -2;
  std::memcpy(out, base + kHdr, n);
  const uint64_t seq = h->seq.load(std::memory_order_acquire);
  h->ack.store(seq, std::memory_order_release);
  h->wack.store(static_cast<uint32_t>(seq), std::memory_order_release);
  futex_wake(&h->wack);
  return static_cast<int64_t>(n);
}

// Zero-copy variant: blocks for the next message, returns its length, and
// leaves the payload in place (caller reads base+64 directly, then calls
// rt_ring_ack). -1 on timeout.
int64_t rt_ring_wait(uint8_t* base, uint64_t last_read, int64_t timeout_ns) {
  Hdr* h = hdr(base);
  const int64_t deadline = timeout_ns < 0 ? -1 : now_ns() + timeout_ns;
  while (h->seq.load(std::memory_order_acquire) <= last_read) {
    if (deadline >= 0 && now_ns() > deadline) return -1;
    futex_wait(&h->wseq, static_cast<uint32_t>(last_read), kSliceNs);
  }
  return static_cast<int64_t>(h->size.load(std::memory_order_acquire));
}

void rt_ring_ack(uint8_t* base) {
  Hdr* h = hdr(base);
  const uint64_t seq = h->seq.load(std::memory_order_acquire);
  h->ack.store(seq, std::memory_order_release);
  h->wack.store(static_cast<uint32_t>(seq), std::memory_order_release);
  futex_wake(&h->wack);
}

// Parallel memcpy: splits a large copy across threads. On many-core TPU
// hosts a single-threaded memcpy leaves most of the memory bandwidth on
// the table; the object-store put path calls this for multi-MB payloads.
void rt_parallel_memcpy(uint8_t* dst, const uint8_t* src, uint64_t n,
                        int nthreads) {
  if (nthreads <= 1 || n < (4u << 20)) {
    std::memcpy(dst, src, n);
    return;
  }
  const uint64_t chunk = (n + nthreads - 1) / nthreads;
  std::vector<std::thread> ts;
  ts.reserve(nthreads - 1);
  for (int i = 1; i < nthreads; ++i) {
    const uint64_t off = uint64_t(i) * chunk;
    if (off >= n) break;
    const uint64_t len = std::min(chunk, n - off);
    ts.emplace_back([=] { std::memcpy(dst + off, src + off, len); });
  }
  std::memcpy(dst, src, std::min(chunk, n));
  for (auto& t : ts) t.join();
}

}  // extern "C"
