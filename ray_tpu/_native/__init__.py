"""Native (C++) runtime components, loaded via ctypes.

The shared library is JIT-compiled from ring.cc with g++ on first use and
cached by source hash (no pybind11 in the target image; the C ABI +
ctypes keeps the binding layer dependency-free). Everything using this
module must degrade gracefully when `get_lib()` returns None (no
toolchain, exotic platform): the pure-Python paths stay correct, just
slower.
"""

from __future__ import annotations

import ctypes
import hashlib
import logging
import os
import subprocess
import sys
import tempfile
import threading

logger = logging.getLogger(__name__)

_lock = threading.Lock()
_lib = None
_tried = False
_build_started = False

_SRC = os.path.join(os.path.dirname(os.path.abspath(__file__)), "ring.cc")


def _build_dir() -> str:
    d = os.environ.get("RT_NATIVE_BUILD_DIR") or os.path.join(
        tempfile.gettempdir(), f"rt_native_{os.geteuid()}")
    os.makedirs(d, exist_ok=True)
    return d


def _compile() -> str | None:
    with open(_SRC, "rb") as f:
        src = f.read()
    tag = hashlib.sha256(src).hexdigest()[:16]
    out = os.path.join(_build_dir(), f"librt_native_{tag}.so")
    if os.path.exists(out):
        return out
    tmp = out + f".{os.getpid()}.tmp"
    cmd = ["g++", "-O3", "-shared", "-fPIC", "-std=c++17", "-pthread",
           _SRC, "-o", tmp]
    try:
        subprocess.run(cmd, check=True, capture_output=True, timeout=120)
        os.replace(tmp, out)  # atomic: concurrent builders converge
        return out
    except Exception as e:
        logger.warning("native build failed (%r); using pure-Python paths", e)
        try:
            os.unlink(tmp)
        except OSError:
            pass
        return None


def get_lib():
    """The loaded native library, or None if unavailable."""
    global _lib, _tried
    if _lib is not None or _tried:
        return _lib
    with _lock:
        if _lib is not None or _tried:
            return _lib
        _tried = True
        if os.environ.get("RT_DISABLE_NATIVE"):
            return None
        path = _compile()
        if path is None:
            return None
        try:
            lib = ctypes.CDLL(path)
        except OSError as e:
            logger.warning("native load failed (%r)", e)
            return None
        lib.rt_ring_write.restype = ctypes.c_int
        lib.rt_ring_write.argtypes = [
            ctypes.c_void_p, ctypes.c_uint64, ctypes.c_char_p,
            ctypes.c_uint64, ctypes.c_int64]
        lib.rt_ring_read.restype = ctypes.c_int64
        lib.rt_ring_read.argtypes = [
            ctypes.c_void_p, ctypes.c_uint64, ctypes.c_void_p,
            ctypes.c_uint64, ctypes.c_int64]
        lib.rt_ring_wait.restype = ctypes.c_int64
        lib.rt_ring_wait.argtypes = [
            ctypes.c_void_p, ctypes.c_uint64, ctypes.c_int64]
        lib.rt_ring_ack.restype = None
        lib.rt_ring_ack.argtypes = [ctypes.c_void_p]
        lib.rt_parallel_memcpy.restype = None
        lib.rt_parallel_memcpy.argtypes = [
            ctypes.c_void_p, ctypes.c_void_p, ctypes.c_uint64, ctypes.c_int]
        _lib = lib
        return _lib


def _buffer_address(mv: memoryview) -> int:
    """Address of a contiguous buffer, writable or readonly (numpy views a
    readonly buffer without copying)."""
    try:
        return ctypes.addressof((ctypes.c_char * len(mv)).from_buffer(mv))
    except TypeError:  # readonly
        import numpy as np

        return np.frombuffer(mv, dtype=np.uint8).ctypes.data


def get_lib_nowait():
    """Like get_lib() but NEVER blocks on a compile: returns the lib only if
    already built, kicking off a background build otherwise. Hot paths that
    merely prefer native (e.g. the object store's copy under its lock) use
    this so the first big put never stalls the whole object plane behind a
    g++ invocation."""
    global _build_started
    if _lib is not None or _tried:
        return _lib
    if not _lock.acquire(blocking=False):
        return None  # a build is in progress on another thread
    try:
        if _lib is not None or _tried or _build_started:
            return _lib
        # Flag under the lock BEFORE spawning: _tried only flips once the
        # build thread itself re-acquires the lock, so without this every
        # caller winning the non-blocking acquire first would spawn another
        # duplicate g++ build.
        _build_started = True
        threading.Thread(target=get_lib, daemon=True,
                         name="rt-native-build").start()
        return None
    finally:
        _lock.release()


def parallel_memcpy(dst_mv: memoryview, src, nthreads: int | None = None) -> bool:
    """Copy `src` (bytes-like) into `dst_mv` with the native threaded copy.
    Returns False (caller should fall back) when the lib is unavailable."""
    lib = get_lib_nowait()
    if lib is None:
        return False
    if nthreads is None:
        nthreads = min(8, os.cpu_count() or 1)
    src_mv = memoryview(src).cast("B")
    n = len(src_mv)
    if len(dst_mv) < n:
        raise ValueError("destination smaller than source")
    lib.rt_parallel_memcpy(_buffer_address(memoryview(dst_mv).cast("B")),
                           _buffer_address(src_mv), n, nthreads)
    return True
