"""Application metrics: Counter / Gauge / Histogram.

Parity target: reference python/ray/util/metrics.py (Metric:23, Counter:90,
Gauge:158, Histogram:216) backed by src/ray/stats/metric.h. Records are
batched from each worker to the controller (the reference exports to its
metrics agent / Prometheus); aggregated series are served by the state API
(`ray_tpu.util.state.metrics()`) and the dashboard's /api/metrics endpoint,
including a Prometheus text rendering.
"""

from __future__ import annotations

import threading
import time
from typing import Optional, Sequence

_lock = threading.Lock()
_pending: list[dict] = []  # batched records awaiting flush
_flusher_started = False
_FLUSH_INTERVAL_S = 1.0


def _flush_loop():
    while True:
        time.sleep(_FLUSH_INTERVAL_S)
        _flush_now()


def ensure_flusher() -> None:
    """Start the background flusher if it isn't running — for sources that
    report through drain hooks (device-object residency) rather than
    minting records directly, in processes that might never do the latter."""
    global _flusher_started
    with _lock:
        if _flusher_started:
            return
        _flusher_started = True
    threading.Thread(target=_flush_loop, daemon=True,
                     name="rt-metrics-flush").start()


def _flush_now(force: bool = False):
    from ray_tpu._private.worker import global_worker

    _drain_task_dispatch()
    _drain_device_objects()
    _drain_pipeline_occupancy()
    _drain_data_exchange()
    # Tracing spans piggyback on the metrics flush batches (README "Tracing
    # & timeline"): one push per tick carries both — no extra connection,
    # cadence, or frame. sys.modules gate: a process that never traced must
    # not import (or pay for) the tracing module here.
    import sys

    spans = None
    _tr = sys.modules.get("ray_tpu._private.tracing")
    if _tr is not None:
        try:
            spans = _tr.drain() or None
        except Exception:
            spans = None
    # Cluster lifecycle events ride the same batches (`events=` key —
    # README "Cluster events"), with the same sys.modules gate: a process
    # that never emitted must not import (or pay for) the events module.
    events = None
    _ev = sys.modules.get("ray_tpu._private.events")
    if _ev is not None:
        try:
            events = _ev.drain() or None
        except Exception:
            events = None
    with _lock:
        global _pending
        batch, _pending = _pending, []
    if not batch and not spans and not events:
        return
    w = global_worker()
    if w is None or (getattr(w, "_shutdown", False) and not force):
        if w is not None:
            # A background tick racing Worker.disconnect between its
            # `_shutdown = True` and flush_on_shutdown(): put the drained
            # records/spans/events BACK so the force flush still finds them
            # — silently dropping here would re-open the tail-loss hole
            # this path exists to close.
            with _lock:
                _pending[:0] = batch
            if spans and _tr is not None:
                try:
                    _tr.requeue(spans)
                except Exception:
                    pass
            if events and _ev is not None:
                try:
                    _ev.requeue(events)
                except Exception:
                    pass
        return
    try:
        kw: dict = {"records": batch}
        if spans is not None:
            kw["spans"] = spans
        if events is not None:
            kw["events"] = events
        w.controller.push_threadsafe("metrics_report", **kw)
    except Exception:
        pass


def flush_on_shutdown():
    """Best-effort FINAL flush, called from Worker.disconnect(): without it
    a short-lived driver silently drops up to one flush interval of
    trailing counters and spans (the flusher refuses to push once
    `_shutdown` is set). The trailing `ping` call fences the push: both
    ride the same FIFO connection, so when the ping returns the controller
    has already processed the final batch."""
    from ray_tpu._private.worker import global_worker

    w = global_worker()
    if w is None or w.controller is None:
        return
    _flush_now(force=True)
    try:
        w.io.run(w.controller.call("ping"), timeout=2)
    except Exception:
        pass


def _record(rec: dict):
    with _lock:
        _pending.append(rec)
    ensure_flusher()


# --- task dispatch route counters ------------------------------------------
# Which path task submissions take: "direct" (owner-side leased dispatch,
# the controller never sees the task) vs "controller" (classic central
# dispatch: TPU tasks, RT_DIRECT_DISPATCH=0, direct-dispatch failover).
# The hot path pays one lock+int per submission; the per-path Counter
# records are minted once per flush interval from the accumulated deltas.
_task_dispatch_lock = threading.Lock()
_task_dispatch_counts = {"direct": 0, "controller": 0}
_task_dispatch_totals = {"direct": 0, "controller": 0}


def record_task_dispatch(path: str, n: int = 1) -> None:
    """Count `n` task submissions routed via `path` ('direct' or
    'controller'). Called from the submit hot paths — keep it cheap."""
    with _task_dispatch_lock:
        _task_dispatch_counts[path] = _task_dispatch_counts.get(path, 0) + n
        _task_dispatch_totals[path] = _task_dispatch_totals.get(path, 0) + n
    ensure_flusher()


def task_dispatch_counts() -> dict:
    """Process-local lifetime totals per dispatch path (tests/diagnostics —
    no controller round trip)."""
    with _task_dispatch_lock:
        return dict(_task_dispatch_totals)


def _drain_task_dispatch() -> None:
    with _task_dispatch_lock:
        deltas = {p: v for p, v in _task_dispatch_counts.items() if v}
        for p in deltas:
            _task_dispatch_counts[p] = 0
    for path, v in deltas.items():
        TASKS_DISPATCHED.inc(v, tags={"path": path})


# --- device object residency -------------------------------------------
# Gauges for the device object plane (README "Device objects"): how many
# produced arrays are pinned in THIS process's DeviceObjectTable and how
# many bytes of (device) memory they hold. Tagged per worker — the
# controller aggregates last-value-wins per tag set, so each producer's
# residency stays visible. Drained from the table on each flush tick; a
# mint per pin/free would put a metrics record on the result hot path.
_last_device_stats: dict | None = None


def reset_device_stats_cache() -> None:
    """Forget per-session report caches (called on worker shutdown): a
    NEW session's controller starts with no gauge state, so the first
    drain there must report even if the values happen to match the
    previous session's final report — and histogram bucket boundaries
    (registered once per session via `histogram_decl` records) must be
    re-declared to the fresh controller."""
    global _last_device_stats, _last_data_stats
    _last_device_stats = None
    _last_data_stats = None
    _hist_declared.clear()


def _drain_device_objects() -> None:
    global _last_device_stats
    import sys

    ds = sys.modules.get("ray_tpu._private.device_store")
    if ds is None:
        return  # plane never touched in this process
    try:
        stats = ds.table_stats()
    except Exception:
        return
    if stats == _last_device_stats:
        return  # last-value-wins gauge: re-reporting a flat value is noise
    _last_device_stats = stats
    from ray_tpu._private.worker import global_worker

    w = global_worker()
    tags = {"worker_id": (w.worker_id[:12] if w is not None else "")}
    DEVICE_OBJECTS_COUNT.set(stats["count"], tags=tags)
    DEVICE_OBJECTS_BYTES.set(stats["bytes"], tags=tags)


_last_data_stats: dict | None = None


def _drain_data_exchange() -> None:
    """Data-plane exchange gauges/counters, one sample per flush window.
    sys.modules gate: only processes that drove or executed an exchange
    ever import data._internal.exchange."""
    global _last_data_stats
    import sys

    xch = sys.modules.get("ray_tpu.data._internal.exchange")
    if xch is None:
        return
    try:
        stats = xch.exchange_stats()
    except Exception:
        return
    if stats == _last_data_stats:
        return  # last-value-wins gauges: a flat re-report is noise
    prev = _last_data_stats or {}
    _last_data_stats = stats
    DATA_BLOCKS_INFLIGHT.set(stats["blocks_inflight"])
    for key, metric in (("spilled_bytes", DATA_SPILLED_BYTES),
                        ("bp_stalls", DATA_BP_STALLS)):
        delta = stats[key] - prev.get(key, 0)
        if delta > 0:
            metric.inc(delta)


def _drain_pipeline_occupancy() -> None:
    """Per-stage pipeline occupancy/bubble gauges, one sample per flush
    window. sys.modules gate: only processes hosting a PipelineStage ever
    import llm.pipeline, so everyone else skips the drain entirely."""
    import sys

    pp = sys.modules.get("ray_tpu.llm.pipeline")
    if pp is None:
        return
    try:
        occ = pp.occupancy_snapshot("metrics")
    except Exception:
        return
    for stage, frac in occ.items():
        LLM_PP_OCCUPANCY.set(frac, tags={"stage": stage})
        LLM_PP_BUBBLE.set(max(0.0, 1.0 - frac), tags={"stage": stage})


class Metric:
    def __init__(self, name: str, description: str = "",
                 tag_keys: Optional[Sequence[str]] = None):
        if not name:
            raise ValueError("metric name is required")
        self._name = name
        self._description = description
        self._tag_keys = tuple(tag_keys or ())
        self._default_tags: dict = {}

    def set_default_tags(self, tags: dict) -> "Metric":
        self._default_tags = dict(tags)
        return self

    def _tags(self, tags: Optional[dict]) -> dict:
        merged = dict(self._default_tags)
        if tags:
            merged.update(tags)
        extra = set(merged) - set(self._tag_keys)
        if extra:
            raise ValueError(f"unknown tag keys {sorted(extra)}; declared {self._tag_keys}")
        return merged

    @property
    def info(self) -> dict:
        return {"name": self._name, "description": self._description,
                "tag_keys": self._tag_keys}


class Counter(Metric):
    """Monotonically increasing value (reference metrics.py:90)."""

    def inc(self, value: float = 1.0, tags: Optional[dict] = None):
        if value <= 0:
            raise ValueError("Counter.inc requires value > 0")
        _record({"kind": "counter", "name": self._name,
                 "desc": self._description, "tags": self._tags(tags),
                 "value": float(value)})


class Gauge(Metric):
    """Last-value-wins measurement (reference metrics.py:158)."""

    def set(self, value: float, tags: Optional[dict] = None):
        _record({"kind": "gauge", "name": self._name,
                 "desc": self._description, "tags": self._tags(tags),
                 "value": float(value)})


#: (name, boundaries-tuple) pairs already declared to the controller by this
#: process. Bucket boundaries ride ONE `histogram_decl` record per pair
#: instead of every observe — the tracing plane's hot-path histograms (RPC
#: frame RTT, decode-step) would otherwise ship the same boundary list in
#: every record of every flush batch. GIL-atomic set ops; a rare duplicate
#: decl under a race is idempotent controller-side.
_hist_declared: set = set()


class Histogram(Metric):
    """Bucketed distribution (reference metrics.py:216)."""

    def __init__(self, name: str, description: str = "",
                 boundaries: Optional[Sequence[float]] = None,
                 tag_keys: Optional[Sequence[str]] = None):
        super().__init__(name, description, tag_keys)
        if not boundaries:
            raise ValueError("Histogram requires bucket boundaries")
        self._boundaries = sorted(float(b) for b in boundaries)

    def observe(self, value: float, tags: Optional[dict] = None):
        key = (self._name, tuple(self._boundaries))
        if key not in _hist_declared:
            _hist_declared.add(key)
            _record({"kind": "histogram_decl", "name": self._name,
                     "desc": self._description,
                     "boundaries": self._boundaries})
        _record({"kind": "histogram", "name": self._name,
                 "desc": self._description, "tags": self._tags(tags),
                 "value": float(value)})


#: Tasks submitted per dispatch route (see record_task_dispatch): the
#: direct-vs-controller split is THE health signal for owner-side dispatch —
#: a rising "controller" share under RT_DIRECT_DISPATCH=1 means failovers.
TASKS_DISPATCHED = Counter(
    "rt_tasks_dispatched_total",
    description="tasks submitted, by dispatch path",
    tag_keys=("path",))

#: Device object plane residency (see _drain_device_objects): entries and
#: bytes pinned in each producer's DeviceObjectTable. A count that only
#: grows means owner-side frees are not reaching producers.
DEVICE_OBJECTS_COUNT = Gauge(
    "rt_device_objects_count",
    description="arrays pinned in this worker's device object table",
    tag_keys=("worker_id",))
DEVICE_OBJECTS_BYTES = Gauge(
    "rt_device_objects_bytes",
    description="bytes pinned in this worker's device object table",
    tag_keys=("worker_id",))

#: Data-plane exchange pressure (see _drain_data_exchange, README "Data
#: plane"): blocks in flight is the live map-wave width (bounded by
#: RT_DATA_MAX_INFLIGHT_BLOCKS); spilled bytes counts shards pushed through
#: the storage plane under memory pressure; stalls counts submit-loop
#: pauses on store backpressure. Spills/stalls at nominal load mean the
#: in-flight budget is too wide for the store.
DATA_BLOCKS_INFLIGHT = Gauge(
    "rt_data_blocks_inflight",
    description="exchange block tasks currently in flight")
DATA_SPILLED_BYTES = Counter(
    "rt_data_spilled_bytes_total",
    description="exchange shard bytes spilled through the storage plane")
DATA_BP_STALLS = Counter(
    "rt_data_bp_stalls_total",
    description="exchange submit-loop stalls on store backpressure")

#: Checkpoint engine (README "Checkpointing & storage"), minted at each
#: manifest commit by train/checkpoint.py. save_seconds is snapshot->commit
#: wall time tagged by mode (async saves run off the step path; their
#: duration is hidden from training, sync ones are on it); a bytes/committed
#: ratio drifting up means checkpoints are growing.
CHECKPOINT_SAVE_SECONDS = Histogram(
    "rt_checkpoint_save_seconds",
    description="checkpoint save duration, snapshot to manifest commit",
    boundaries=[0.01, 0.05, 0.25, 1.0, 5.0, 30.0, 120.0, 600.0],
    tag_keys=("mode",))
CHECKPOINT_BYTES = Counter(
    "rt_checkpoint_bytes_total",
    description="bytes committed to checkpoint storage")
CHECKPOINT_COMMITTED = Counter(
    "rt_checkpoint_committed_total",
    description="checkpoints committed (manifest rename succeeded)")

#: Serve admission control (README "Overload & admission control"), minted
#: router-side (proxy process or handle owner). Sheds are the plane working
#: as designed under overload; a nonzero rate at NOMINAL load means budgets
#: are set too tight. Queue depth is the per-deployment router backlog —
#: pinned at max_queued_requests while shedding, draining to zero after.
SERVE_SHED = Counter(
    "rt_serve_shed_total",
    description="serve requests shed by admission control",
    tag_keys=("deployment", "reason"))
SERVE_QUEUE_DEPTH = Gauge(
    "rt_serve_queue_depth",
    description="requests waiting in this router's deployment queue",
    tag_keys=("deployment",))

#: Push-stream producer counters (README "Cross-host streaming &
#: multi-proxy"), minted replica-side as coalesced s_data frames leave the
#: send window. records/bytes track throughput of the cross-host token
#: path; parks counts write() episodes that hit window exhaustion — a
#: sustained park rate means the consumer (proxy/SSE client) is the
#: bottleneck, not the replica.
STREAM_PUSH_RECORDS = Counter(
    "rt_stream_push_records_total",
    description="records sent over the push-stream transport")
STREAM_PUSH_BYTES = Counter(
    "rt_stream_push_bytes_total",
    description="record bytes sent over the push-stream transport")
STREAM_PUSH_PARKS = Counter(
    "rt_stream_push_parks_total",
    description="push-stream write parks on an exhausted send window")

#: Per-proxy ingress counters: with N proxies behind one endpoint these
#: attribute load to the process that carried it (the aggregate is the
#: cluster's serving ingress rate). active_streams is the live SSE count
#: per proxy — the fan-out the stream thread pool is actually holding.
SERVE_PROXY_REQS = Counter(
    "rt_serve_proxy_requests_total",
    description="HTTP requests handled, by proxy process",
    tag_keys=("proxy",))
SERVE_PROXY_STREAMS = Counter(
    "rt_serve_proxy_streams_total",
    description="SSE streams opened, by proxy process",
    tag_keys=("proxy",))
SERVE_PROXY_ACTIVE = Gauge(
    "rt_serve_proxy_active_streams",
    description="SSE streams currently open, by proxy process",
    tag_keys=("proxy",))

#: Per-attempt execution deadlines that fired (@remote(timeout_s=...)),
#: minted worker-side as the deadline interrupts the attempt. A non-zero
#: rate under a healthy workload means timeout_s is set too tight — or
#: something really is wedging tasks (cross-check rt_stalls_total).
TASK_TIMEOUTS = Counter(
    "rt_task_timeouts_total",
    description="task attempts killed by their per-attempt timeout_s")

#: Tracing-plane latency histograms (README "Tracing & timeline"), observed
#: ONLY inside sampled trace contexts — the unsampled hot path mints no
#: records. Frame RTT catches control-plane hops a span tree summarizes;
#: decode-step is the serve->engine host-link sync the BENCH_r05 22x gap
#: hides in (each observation is one engine host readback round trip).
RPC_FRAME_SECONDS = Histogram(
    "rt_rpc_frame_seconds",
    description="traced RPC request round-trip time",
    boundaries=[0.0002, 0.001, 0.005, 0.02, 0.1, 0.5, 2.0, 10.0],
    tag_keys=("method",))
DECODE_STEP_SECONDS = Histogram(
    "rt_decode_step_seconds",
    description="llm engine host-sync readback duration per decode drain",
    boundaries=[0.0005, 0.002, 0.01, 0.05, 0.2, 1.0, 5.0])

#: Pipeline-parallel serving (README "Pipeline-parallel serving"), drained
#: each flush tick in processes hosting a PipelineStage: occupancy is the
#: stage's busy fraction of the tick window, bubble its complement. A
#: persistently low-occupancy stage is the pipeline's bubble source —
#: rebalance the layer split or raise the microbatch count.
LLM_PP_OCCUPANCY = Gauge(
    "rt_llm_pp_occupancy",
    description="pipeline stage busy fraction over the last flush window",
    tag_keys=("stage",))
LLM_PP_BUBBLE = Gauge(
    "rt_llm_pp_bubble",
    description="pipeline stage idle (bubble) fraction over the last "
                "flush window",
    tag_keys=("stage",))

#: Stall escalations are aggregated controller-side from StallReports
#: (`rt_stalls_total{stage=warn|dump|kill}` — see controller._p_stall_report);
#: no worker-side series exists because a stalled worker may be too wedged
#: to flush metrics at all.
