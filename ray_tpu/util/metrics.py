"""Application metrics: Counter / Gauge / Histogram.

Parity target: reference python/ray/util/metrics.py (Metric:23, Counter:90,
Gauge:158, Histogram:216) backed by src/ray/stats/metric.h. Records are
batched from each worker to the controller (the reference exports to its
metrics agent / Prometheus); aggregated series are served by the state API
(`ray_tpu.util.state.metrics()`) and the dashboard's /api/metrics endpoint,
including a Prometheus text rendering.
"""

from __future__ import annotations

import threading
import time
from typing import Optional, Sequence

_lock = threading.Lock()
_pending: list[dict] = []  # batched records awaiting flush
_flusher_started = False
_FLUSH_INTERVAL_S = 1.0


def _flush_loop():
    while True:
        time.sleep(_FLUSH_INTERVAL_S)
        _flush_now()


def _flush_now():
    from ray_tpu._private.worker import global_worker

    _drain_task_dispatch()
    with _lock:
        global _pending
        if not _pending:
            return
        batch, _pending = _pending, []
    w = global_worker()
    if w is None or getattr(w, "_shutdown", False):
        return
    try:
        w.controller.push_threadsafe("metrics_report", records=batch)
    except Exception:
        pass


def _record(rec: dict):
    global _flusher_started
    with _lock:
        _pending.append(rec)
        if not _flusher_started:
            _flusher_started = True
            threading.Thread(target=_flush_loop, daemon=True,
                             name="rt-metrics-flush").start()


# --- task dispatch route counters ------------------------------------------
# Which path task submissions take: "direct" (owner-side leased dispatch,
# the controller never sees the task) vs "controller" (classic central
# dispatch: TPU tasks, RT_DIRECT_DISPATCH=0, direct-dispatch failover).
# The hot path pays one lock+int per submission; the per-path Counter
# records are minted once per flush interval from the accumulated deltas.
_task_dispatch_lock = threading.Lock()
_task_dispatch_counts = {"direct": 0, "controller": 0}
_task_dispatch_totals = {"direct": 0, "controller": 0}


def record_task_dispatch(path: str, n: int = 1) -> None:
    """Count `n` task submissions routed via `path` ('direct' or
    'controller'). Called from the submit hot paths — keep it cheap."""
    global _flusher_started
    with _task_dispatch_lock:
        _task_dispatch_counts[path] = _task_dispatch_counts.get(path, 0) + n
        _task_dispatch_totals[path] = _task_dispatch_totals.get(path, 0) + n
    with _lock:
        if not _flusher_started:
            _flusher_started = True
            threading.Thread(target=_flush_loop, daemon=True,
                             name="rt-metrics-flush").start()


def task_dispatch_counts() -> dict:
    """Process-local lifetime totals per dispatch path (tests/diagnostics —
    no controller round trip)."""
    with _task_dispatch_lock:
        return dict(_task_dispatch_totals)


def _drain_task_dispatch() -> None:
    with _task_dispatch_lock:
        deltas = {p: v for p, v in _task_dispatch_counts.items() if v}
        for p in deltas:
            _task_dispatch_counts[p] = 0
    for path, v in deltas.items():
        TASKS_DISPATCHED.inc(v, tags={"path": path})


class Metric:
    def __init__(self, name: str, description: str = "",
                 tag_keys: Optional[Sequence[str]] = None):
        if not name:
            raise ValueError("metric name is required")
        self._name = name
        self._description = description
        self._tag_keys = tuple(tag_keys or ())
        self._default_tags: dict = {}

    def set_default_tags(self, tags: dict) -> "Metric":
        self._default_tags = dict(tags)
        return self

    def _tags(self, tags: Optional[dict]) -> dict:
        merged = dict(self._default_tags)
        if tags:
            merged.update(tags)
        extra = set(merged) - set(self._tag_keys)
        if extra:
            raise ValueError(f"unknown tag keys {sorted(extra)}; declared {self._tag_keys}")
        return merged

    @property
    def info(self) -> dict:
        return {"name": self._name, "description": self._description,
                "tag_keys": self._tag_keys}


class Counter(Metric):
    """Monotonically increasing value (reference metrics.py:90)."""

    def inc(self, value: float = 1.0, tags: Optional[dict] = None):
        if value <= 0:
            raise ValueError("Counter.inc requires value > 0")
        _record({"kind": "counter", "name": self._name,
                 "desc": self._description, "tags": self._tags(tags),
                 "value": float(value)})


class Gauge(Metric):
    """Last-value-wins measurement (reference metrics.py:158)."""

    def set(self, value: float, tags: Optional[dict] = None):
        _record({"kind": "gauge", "name": self._name,
                 "desc": self._description, "tags": self._tags(tags),
                 "value": float(value)})


class Histogram(Metric):
    """Bucketed distribution (reference metrics.py:216)."""

    def __init__(self, name: str, description: str = "",
                 boundaries: Optional[Sequence[float]] = None,
                 tag_keys: Optional[Sequence[str]] = None):
        super().__init__(name, description, tag_keys)
        if not boundaries:
            raise ValueError("Histogram requires bucket boundaries")
        self._boundaries = sorted(float(b) for b in boundaries)

    def observe(self, value: float, tags: Optional[dict] = None):
        _record({"kind": "histogram", "name": self._name,
                 "desc": self._description, "tags": self._tags(tags),
                 "value": float(value), "boundaries": self._boundaries})


#: Tasks submitted per dispatch route (see record_task_dispatch): the
#: direct-vs-controller split is THE health signal for owner-side dispatch —
#: a rising "controller" share under RT_DIRECT_DISPATCH=1 means failovers.
TASKS_DISPATCHED = Counter(
    "rt_tasks_dispatched_total",
    description="tasks submitted, by dispatch path",
    tag_keys=("path",))
