"""multiprocessing.Pool API over cluster tasks.

Parity target: reference python/ray/util/multiprocessing/pool.py — drop-in
Pool so `from multiprocessing import Pool` code scales past one machine by
switching the import.
"""

from __future__ import annotations

import itertools
from typing import Any, Callable, Iterable, Optional

import ray_tpu


class AsyncResult:
    def __init__(self, refs: list, single: bool):
        self._refs = refs
        self._single = single

    def get(self, timeout: Optional[float] = None):
        out = ray_tpu.get(self._refs, timeout=timeout)
        return out[0] if self._single else out

    def wait(self, timeout: Optional[float] = None):
        ray_tpu.wait(self._refs, num_returns=len(self._refs), timeout=timeout)

    def ready(self) -> bool:
        done, _ = ray_tpu.wait(self._refs, num_returns=len(self._refs),
                               timeout=0)
        return len(done) == len(self._refs)

    def successful(self) -> bool:
        try:
            self.get(timeout=0.001)
            return True
        except Exception:
            return False


class Pool:
    """Task-backed process pool. `processes` caps in-flight submissions on
    the synchronous paths (map/starmap/imap*); the async paths submit
    eagerly and rely on cluster CPUs for limiting."""

    def __init__(self, processes: Optional[int] = None):
        self._processes = processes
        self._closed = False

        @ray_tpu.remote
        def _run(fn, args, kwargs):
            return fn(*args, **(kwargs or {}))

        self._run = _run

    def apply(self, fn: Callable, args: tuple = (), kwds: dict | None = None):
        return self.apply_async(fn, args, kwds).get()

    def apply_async(self, fn: Callable, args: tuple = (),
                    kwds: dict | None = None) -> AsyncResult:
        assert not self._closed, "Pool is closed"
        return AsyncResult([self._run.remote(fn, tuple(args), kwds)], True)

    def _windowed(self, submits: list) -> list:
        """Run thunks with at most `processes` in flight."""
        if not self._processes:
            return [t() for t in submits]
        out = [None] * len(submits)
        in_flight: dict = {}
        i = 0
        while i < len(submits) or in_flight:
            while i < len(submits) and len(in_flight) < self._processes:
                out[i] = submits[i]()
                in_flight[out[i]] = i
                i += 1
            if in_flight:
                done, _ = ray_tpu.wait(list(in_flight), num_returns=1,
                                       timeout=10)
                for d in done:
                    in_flight.pop(d, None)
        return out

    def map(self, fn: Callable, iterable: Iterable,
            chunksize: Optional[int] = None) -> list:
        assert not self._closed, "Pool is closed"
        refs = self._windowed(
            [lambda v=v: self._run.remote(fn, (v,), None) for v in iterable])
        return ray_tpu.get(refs, timeout=None)

    def map_async(self, fn: Callable, iterable: Iterable,
                  chunksize: Optional[int] = None) -> AsyncResult:
        assert not self._closed, "Pool is closed"
        refs = [self._run.remote(fn, (v,), None) for v in iterable]
        return AsyncResult(refs, False)

    def starmap(self, fn: Callable, iterable: Iterable[tuple]) -> list:
        assert not self._closed, "Pool is closed"
        refs = self._windowed(
            [lambda v=v: self._run.remote(fn, tuple(v), None)
             for v in iterable])
        return ray_tpu.get(refs, timeout=None)

    def imap(self, fn: Callable, iterable: Iterable,
             chunksize: Optional[int] = None):
        refs = [self._run.remote(fn, (v,), None) for v in iterable]
        for r in refs:
            yield ray_tpu.get(r, timeout=None)

    def imap_unordered(self, fn: Callable, iterable: Iterable,
                       chunksize: Optional[int] = None):
        pending = [self._run.remote(fn, (v,), None) for v in iterable]
        while pending:
            done, pending = ray_tpu.wait(pending, num_returns=1, timeout=None)
            for d in done:
                yield ray_tpu.get(d, timeout=60)

    def close(self):
        self._closed = True

    def terminate(self):
        self._closed = True

    def join(self):
        pass

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.terminate()
