"""Public scheduling strategy objects.

Parity target: reference python/ray/util/scheduling_strategies.py
(PlacementGroupSchedulingStrategy, NodeAffinitySchedulingStrategy).
"""

from __future__ import annotations

from dataclasses import dataclass

from ray_tpu._private.task_spec import SchedulingStrategy


@dataclass
class NodeAffinitySchedulingStrategy:
    node_id: str
    soft: bool = False

    def to_internal(self) -> SchedulingStrategy:
        return SchedulingStrategy(kind="NODE_AFFINITY", node_id=self.node_id, soft=self.soft)


@dataclass
class PlacementGroupSchedulingStrategy:
    placement_group: object
    placement_group_bundle_index: int = -1
    placement_group_capture_child_tasks: bool = False

    def to_internal(self) -> SchedulingStrategy:
        pg = self.placement_group
        return SchedulingStrategy(
            kind="PLACEMENT_GROUP",
            pg_id=pg.id if hasattr(pg, "id") else pg,
            pg_bundle_index=self.placement_group_bundle_index,
            pg_capture_child_tasks=self.placement_group_capture_child_tasks,
        )
