"""ray_tpu.util — utility layer over the core runtime.

Parity target: reference python/ray/util/ — ActorPool, Queue,
multiprocessing.Pool, collective groups, placement groups, scheduling
strategies, the state API, and chaos tooling.
"""

from ray_tpu._private.watchdog import report_progress
from ray_tpu.util.actor_pool import ActorPool
from ray_tpu.util.placement_group import placement_group
from ray_tpu.util.queue import Empty, Full, Queue

__all__ = [
    "ActorPool",
    "Empty",
    "Full",
    "Queue",
    "placement_group",
    "report_progress",
]
