"""Placement groups: gang reservation of resource bundles across nodes.

Parity target: reference python/ray/util/placement_group.py
(placement_group(), strategies PACK/SPREAD/STRICT_PACK/STRICT_SPREAD) +
GcsPlacementGroupManager/Scheduler. The TPU-era significance: a pod slice is a
gang of hosts; STRICT_SPREAD bundles with per-host TPU chips express "one
worker per TPU host of the slice".
"""

from __future__ import annotations

from ray_tpu._private.ids import PlacementGroupID
from ray_tpu._private.resources import ResourceSet
from ray_tpu._private.worker import global_worker

VALID_STRATEGIES = ("PACK", "SPREAD", "STRICT_PACK", "STRICT_SPREAD")


class PlacementGroup:
    def __init__(self, pg_id: str, bundles: list[dict]):
        self.id = pg_id
        self.bundles = bundles

    def ready(self):
        """Returns an ObjectRef resolving when the PG is placed (parity with
        reference pg.ready())."""
        from ray_tpu.remote_function import RemoteFunction

        pg = self

        def _ready():
            return True

        return (
            RemoteFunction(_ready, {"num_cpus": 0, "placement_group": pg, "name": "pg_ready"})
            .remote()
        )

    def wait(self, timeout_seconds: float = 30.0) -> bool:
        w = global_worker()
        rep = w.io.run(w.controller.call("pg_wait_ready", pg_id=self.id, timeout=timeout_seconds))
        return rep["ready"]

    def __reduce__(self):
        return (PlacementGroup, (self.id, self.bundles))


def placement_group(bundles: list[dict], strategy: str = "PACK", name: str = "") -> PlacementGroup:
    if strategy not in VALID_STRATEGIES:
        raise ValueError(f"Invalid strategy {strategy!r}; must be one of {VALID_STRATEGIES}")
    if not bundles or any(not b for b in bundles):
        raise ValueError("bundles must be a non-empty list of non-empty dicts")
    w = global_worker()
    pg_id = PlacementGroupID.from_random().hex()
    raw = [ResourceSet(b).raw() for b in bundles]
    w.io.run(w.controller.call("create_pg", pg_id=pg_id, bundles=raw, strategy=strategy, name=name))
    return PlacementGroup(pg_id, bundles)


def remove_placement_group(pg: PlacementGroup) -> None:
    w = global_worker()
    w.io.run(w.controller.call("remove_pg", pg_id=pg.id))
