"""Public state API: list cluster entities.

Parity target: reference python/ray/util/state/api.py (list_tasks,
list_actors, list_objects, list_nodes, list_workers — the StateApiClient
surface, backed here by controller queries instead of the dashboard's
aggregator).
"""

from __future__ import annotations

from ray_tpu._private.worker import global_worker


def _call(method: str, **kw):
    w = global_worker()
    if w is None:
        raise RuntimeError("ray_tpu.init() first")
    return w.io.run(w.controller.call(method, **kw), timeout=30)


class TruncatedList(list):
    """A plain list plus a `truncated` flag: the uniform limit contract of
    every list API — when the controller dropped rows beyond `limit=` the
    flag is True instead of the caller silently seeing a short list."""

    truncated: bool = False


def _rows(rep: dict, key: str) -> TruncatedList:
    rows = TruncatedList(rep[key])
    rows.truncated = bool(rep.get("truncated"))
    return rows


def list_tasks(limit: int = 1000) -> list[dict]:
    """Executed tasks (from the task-event ring) plus live queued/running
    ones; each row has task_id/name/kind/state/node/worker/timestamps.
    Rows beyond `limit` drop oldest-first; the returned list's
    `.truncated` is True when that happened."""
    return _rows(_call("list_tasks", limit=limit), "tasks")


def list_objects(limit: int = 1000) -> list[dict]:
    """Directory entries known to the controller. Each row carries a
    `plane` field: "host" for store/inline objects, "device" for entries
    whose payload is pinned in the producing worker's DeviceObjectTable
    (README "Device objects"); device residency totals are the
    `rt_device_objects_{count,bytes}` gauges in `metrics()`. `.truncated`
    on the returned list marks a limit-clipped reply."""
    return _rows(_call("list_objects", limit=limit), "objects")


def list_actors(limit: int = 1000) -> list[dict]:
    snap = _call("state_snapshot")
    out = [{"actor_id": aid, **info} for aid, info in snap["actors"].items()]
    return out[:limit]


def list_nodes() -> list[dict]:
    snap = _call("state_snapshot")
    return [{"node_id": nid, **info} for nid, info in snap["nodes"].items()]


def list_placement_groups() -> list[dict]:
    snap = _call("state_snapshot")
    return [{"pg_id": pid, **info} for pid, info in snap.get("pgs", {}).items()]


def list_checkpoints(path: str | None = None, limit: int = 1000) -> list[dict]:
    """Committed checkpoints. With `path` (any storage-plane URI), the
    directory is scanned directly — committed AND in-flight partial rows,
    no cluster needed. Without it, the cluster-wide registry is queried:
    every engine commit registers best-effort in the controller KV
    (`_checkpoints` namespace), so rows survive the saving worker."""
    if path is not None:
        from ray_tpu.train import checkpoint as ckpt_mod

        return ckpt_mod.list_checkpoints(path)[:limit]
    import json

    rows = []
    for key in _call("kv_keys", ns="_checkpoints", prefix="")["keys"][:limit]:
        val = _call("kv_get", ns="_checkpoints", key=key)["value"]
        if val is None:
            continue
        try:
            rows.append(json.loads(val))
        except ValueError:
            pass
    rows.sort(key=lambda r: r.get("created") or 0)
    return rows


def list_stalls(limit: int = 1000) -> list[dict]:
    """StallReports the controller has aggregated (README "Stall detection
    & watchdogs"): one row per escalation stage crossed anywhere in the
    cluster — worker watchdogs (stage warn/dump/kill), agent backstops
    (beacons stopped), and train group-stall kills. Rows carry the task,
    where it ran, how long it was silent, the flight-recorder tail, and
    (dump/kill) the storage path of the persisted flight dump."""
    return _rows(_call("list_stalls", limit=limit), "stalls")


def list_events(entity: str | None = None, kind: str | None = None,
                severity: str | None = None, since: int | None = None,
                limit: int = 1000) -> list[dict]:
    """Cluster lifecycle events (README "Cluster events"): one row per
    transition the runtime observed — node register/suspect/dead, worker
    start/exit (with normalized cause), actor create/restart/death, lease
    failover and dedup replay, device-object producer loss, checkpoint
    commit/GC, train group restarts, serve deploy/scale/replica death,
    job start/stop, and every stall-escalation stage (carrying the stalled
    task's trace_id). Rows are seq-ordered (controller arrival order).
    `entity=` prefix-matches ANY of an event's entity ids (actor/worker/
    task/lease/node/job ids); `since=` is a seq (exclusive) for follow-
    style polling; `.truncated` marks a limit-clipped reply."""
    kw: dict = {"limit": limit}
    if entity is not None:
        kw["entity"] = entity
    if kind is not None:
        kw["kind"] = kind
    if severity is not None:
        kw["severity"] = severity
    if since is not None:
        kw["since"] = since
    return _rows(_call("list_events", **kw), "events")


def list_traces(limit: int = 1000) -> list[dict]:
    """Traces the controller has indexed (README "Tracing & timeline"):
    one row per trace_id — root name, start/end, span count, and whether
    the root span has landed (`complete`). Arm the plane with RT_TRACING=1
    (+ RT_TRACE_SAMPLE for head-based sampling); export any row with
    `ray-tpu timeline --trace <id>` or `get_trace()`. `.truncated` marks
    a limit-clipped reply."""
    return _rows(_call("list_traces", limit=limit), "traces")


def list_profiles(limit: int = 1000) -> list[dict]:
    """Captured worker profiles (README "Telemetry & profiling"): one
    metadata row per `ray-tpu profile` / `profile_worker` capture, newest
    last — worker/node, mode (cpu|jax), sample counts, and the storage
    path of the persisted document (`/api/profiles?name=` fetches it)."""
    return _rows(_call("list_profiles", limit=limit), "profiles")


def timeseries(series: str | None = None, node_id: str | None = None,
               since: float | None = None) -> list[dict]:
    """Telemetry timeseries rows (README "Telemetry & profiling"): each is
    {node_id, series, worker_id, points=[[ts, value], ...]} with strictly
    monotone timestamps. `series` matches exactly or as a prefix
    ("node." selects the family). Needs RT_TELEMETRY_INTERVAL_S set."""
    kw: dict = {}
    if series is not None:
        kw["series"] = series
    if node_id is not None:
        kw["node_id"] = node_id
    if since is not None:
        kw["since"] = since
    return _call("timeseries", **kw)["series"]


def cluster_utilization() -> dict:
    """Latest telemetry sample per node/worker plus controller self-stats
    (event-loop lag, table sizes) — the data behind `ray-tpu top`.
    {nodes: {node_id: {alive, liveness, beat_age, node: {cpu, mem, ...},
    workers: {wid: {rss, cpu, hbm_used, ...}}}}, controller: {...}}."""
    return _call("cluster_utilization")


def get_trace(trace_id: str) -> dict:
    """Full span list of one trace (unique id prefixes accepted). Falls
    back to the storage plane for traces evicted from the controller ring.
    Returns {found, trace_id, name, start, end, complete, spans}."""
    return _call("get_trace", trace_id=trace_id)


def metrics() -> list[dict]:
    """Aggregated application metrics (ray_tpu.util.metrics Counter/Gauge/
    Histogram series, reference `ray metrics` / Prometheus export)."""
    return _call("get_metrics")["metrics"]


def summarize_tasks() -> dict:
    """Counts by (name, state) — reference `ray summary tasks`."""
    out: dict = {}
    for t in list_tasks(limit=100_000):
        key = (t["name"], t["state"])
        out[key] = out.get(key, 0) + 1
    return {f"{name}:{state}": n for (name, state), n in out.items()}
