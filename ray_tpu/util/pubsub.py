"""Pubsub: subscribe to cluster event channels or publish app events.

Parity target: reference src/ray/pubsub/publisher.h:300 (GCS pubsub) +
python subscriber surface (ray._private.gcs_pubsub). Built-in channels the
controller publishes on: "actor" (lifecycle transitions), "node" (up/down),
"job" (terminal status). Any other channel name is application-defined —
`publish()` fans a payload out to every subscriber of that channel.
"""

from __future__ import annotations

import queue
from typing import Iterable, Optional

from ray_tpu._private.worker import global_worker


def publish(channel: str, payload) -> None:
    """Fan `payload` (any picklable value) out to the channel's subscribers."""
    w = global_worker()
    if w is None:
        raise RuntimeError("ray_tpu.init() first")
    w.controller.push_threadsafe("publish", channel=channel, payload=payload)


class Subscriber:
    """Queue-backed subscription to one or more channels.

    Usage::

        sub = pubsub.subscribe(["actor", "my-channel"])
        ch, payload = sub.poll(timeout=5)   # None on timeout
        sub.close()
    """

    def __init__(self, channels: Iterable[str]):
        self._w = global_worker()
        if self._w is None:
            raise RuntimeError("ray_tpu.init() first")
        self._channels = set(channels)
        self._q: "queue.Queue[tuple]" = queue.Queue()
        self._w.pubsub_listeners.append(self._on_event)
        self._w.io.run(self._w.controller.call(
            "subscribe", channels=sorted(self._channels)), timeout=30)

    def _on_event(self, channel: str, payload):
        if channel in self._channels:
            self._q.put((channel, payload))

    def poll(self, timeout: Optional[float] = None):
        """Next (channel, payload), or None on timeout."""
        try:
            return self._q.get(timeout=timeout)
        except queue.Empty:
            return None

    def __iter__(self):
        while True:
            item = self.poll()
            if item is not None:
                yield item

    def close(self):
        try:
            self._w.pubsub_listeners.remove(self._on_event)
        except ValueError:
            pass
        try:
            self._w.io.run(self._w.controller.call(
                "subscribe", channels=[], unsubscribe=sorted(self._channels)),
                timeout=10)
        except Exception:
            pass


def subscribe(channels) -> Subscriber:
    if isinstance(channels, str):
        channels = [channels]
    return Subscriber(channels)
