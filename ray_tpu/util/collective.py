"""Collective communication library.

Parity target: reference python/ray/util/collective/collective.py
(GroupManager:40, init_collective_group:123, allreduce:268; NCCL/GLOO
backends under util/collective/collective_group/).

TPU-native two-tier design (SURVEY §2.4/§2.5):
- **Device tier**: collective math between chips belongs INSIDE compiled XLA
  programs — `jax.lax.psum/all_gather/ppermute/all_to_all` over mesh axes
  (see ray_tpu.parallel) riding ICI. There is no NCCL-style out-of-band
  device group to manage, so this module doesn't wrap one.
- **Host tier** (this module): cross-process collectives for host data —
  gradient allreduce across TPU hosts (DCN), rendezvous/barriers for worker
  groups, weight broadcast. Implemented over the cluster control plane
  (controller KV as the rendezvous bulletin) with numpy payloads, playing
  the role the reference's GLOO groups play.

Every rank calls init_collective_group(world_size, rank, group_name) first
(reference collective.py:123), then the collectives; calls are matched by a
per-group monotonically increasing sequence number.
"""

from __future__ import annotations

import pickle
import time
from dataclasses import dataclass

import numpy as np

from ray_tpu._private.worker import global_worker


class ReduceOp:
    SUM = "sum"
    PRODUCT = "product"
    MIN = "min"
    MAX = "max"


_REDUCERS = {
    ReduceOp.SUM: lambda arrs: np.sum(arrs, axis=0),
    ReduceOp.PRODUCT: lambda arrs: np.prod(arrs, axis=0),
    ReduceOp.MIN: lambda arrs: np.min(arrs, axis=0),
    ReduceOp.MAX: lambda arrs: np.max(arrs, axis=0),
}


@dataclass
class _Group:
    name: str
    world_size: int
    rank: int
    seq: int = 0

    def __post_init__(self):
        self.written: list[tuple[int, str]] = []  # (seq, key) for lazy GC
        # P2P counters, per peer and per direction — INDEPENDENT of the
        # group seq: p2p matches only (src, dst, nth-message), so an
        # asymmetric send/recv pattern must not desync the group's
        # collective sequence (round-2 advisor finding).
        self.p2p_sent: dict[int, int] = {}
        self.p2p_rcvd: dict[int, int] = {}


class GroupManager:
    """Per-process registry of collective groups (reference GroupManager,
    collective.py:40)."""

    def __init__(self):
        self._groups: dict[str, _Group] = {}

    def create(self, group_name: str, world_size: int, rank: int) -> _Group:
        if not (0 <= rank < world_size):
            raise ValueError(f"rank {rank} out of range for world_size {world_size}")
        g = _Group(group_name, world_size, rank)
        self._groups[group_name] = g
        return g

    def get(self, group_name: str) -> _Group:
        if group_name not in self._groups:
            raise ValueError(
                f"collective group {group_name!r} not initialized in this process; "
                f"call init_collective_group() first")
        return self._groups[group_name]

    def destroy(self, group_name: str):
        self._groups.pop(group_name, None)


_manager = GroupManager()


def init_collective_group(world_size: int, rank: int, group_name: str = "default"):
    """Join this process to a named collective group and rendezvous with the
    other world_size-1 members (reference init_collective_group:123)."""
    g = _manager.create(group_name, world_size, rank)
    _kv_put(f"col/{group_name}/join/{rank}", b"1")
    _wait_all(f"col/{group_name}/join", world_size)
    return g


def destroy_collective_group(group_name: str = "default"):
    _manager.destroy(group_name)


def get_rank(group_name: str = "default") -> int:
    return _manager.get(group_name).rank


def get_collective_group_size(group_name: str = "default") -> int:
    return _manager.get(group_name).world_size


# ------------------------------------------------------------- collectives
def allreduce(tensor, op: str = ReduceOp.SUM, group_name: str = "default"):
    """Allreduce a numpy array (or pytree of arrays) across the group.
    Returns the reduced value (functional — numpy arrays aren't views of
    device memory here, unlike the reference's in-place NCCL semantics)."""
    g = _manager.get(group_name)
    seq = _next_seq(g)
    contribs = _exchange(g, seq, tensor)
    return _tree_reduce(contribs, op)


def allgather(tensor, group_name: str = "default") -> list:
    """Returns [rank0_value, rank1_value, ...]."""
    g = _manager.get(group_name)
    seq = _next_seq(g)
    return _exchange(g, seq, tensor)


def broadcast(tensor, src_rank: int = 0, group_name: str = "default"):
    g = _manager.get(group_name)
    seq = _next_seq(g)
    key = f"col/{g.name}/{seq}/bcast"
    if g.rank == src_rank:
        _put_seq(g, seq, key, pickle.dumps(tensor, protocol=5))
        _barrier_inner(g, seq)
        return tensor
    blob = _kv_wait(key)
    out = pickle.loads(blob)
    _barrier_inner(g, seq)
    return out


def reducescatter(tensor, op: str = ReduceOp.SUM, group_name: str = "default"):
    """Reduce across the group, return this rank's 1/world_size slice along
    axis 0 (reference reducescatter)."""
    g = _manager.get(group_name)
    reduced = allreduce(tensor, op, group_name)
    chunks = np.array_split(np.asarray(reduced), g.world_size, axis=0)
    return chunks[g.rank]


def barrier(group_name: str = "default"):
    g = _manager.get(group_name)
    seq = _next_seq(g)
    _barrier_inner(g, seq)


def send(tensor, dst_rank: int, group_name: str = "default"):
    """P2P send (reference collective.send); matched by the per-(src,dst)
    message counter — deliberately NOT the group seq, so asymmetric p2p
    patterns can't desync the group's collectives."""
    g = _manager.get(group_name)
    n = g.p2p_sent[dst_rank] = g.p2p_sent.get(dst_rank, 0) + 1
    _kv_put(f"col/{g.name}/p2p/{g.rank}->{dst_rank}/{n}",
            pickle.dumps(tensor, protocol=5))


def recv(src_rank: int, group_name: str = "default"):
    g = _manager.get(group_name)
    n = g.p2p_rcvd[src_rank] = g.p2p_rcvd.get(src_rank, 0) + 1
    key = f"col/{g.name}/p2p/{src_rank}->{g.rank}/{n}"
    blob = _kv_wait(key)
    # The receiver is this key's only reader: delete it immediately (the
    # lazy two-rounds-back GC can't cover p2p — there is no rendezvous
    # proving the peer has passed).
    try:
        _worker().kv("del", ns="collective", key=key)
    except Exception:
        pass
    return pickle.loads(blob)


# ---------------------------------------------------------------- plumbing
def _worker():
    w = global_worker()
    if w is None:
        raise RuntimeError("ray_tpu.init() must be called before collectives")
    return w


def _kv_put(key: str, value: bytes):
    _worker().kv("put", ns="collective", key=key, value=value)


def _kv_get(key: str):
    return _worker().kv("get", ns="collective", key=key)["value"]


def _kv_wait(key: str, timeout: float = 120.0, interval: float = 0.003) -> bytes:
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        v = _kv_get(key)
        if v is not None:
            return v
        time.sleep(interval)
    raise TimeoutError(f"collective timeout waiting for {key}")


def _wait_all(prefix: str, world_size: int, timeout: float = 120.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        keys = _worker().kv("keys", ns="collective", prefix=prefix)["keys"]
        if len(keys) >= world_size:
            return
        time.sleep(0.003)
    raise TimeoutError(f"collective rendezvous timeout on {prefix}")


def _next_seq(g: _Group) -> int:
    g.seq += 1
    # GC this rank's keys from two rounds back: every rank has passed that
    # round's rendezvous, so nobody can still be reading them. Keeps the
    # controller KV bounded under per-step allreduce loops.
    horizon = g.seq - 2
    old = [(s, k) for (s, k) in g.written if s <= horizon]
    g.written = [(s, k) for (s, k) in g.written if s > horizon]
    for _, k in old:
        try:
            _worker().kv("del", ns="collective", key=k)
        except Exception:
            pass
    return g.seq


def _put_seq(g: _Group, seq: int, key: str, value: bytes):
    _kv_put(key, value)
    g.written.append((seq, key))


def _exchange(g: _Group, seq: int, tensor) -> list:
    """All ranks publish their contribution, then read everyone's."""
    _put_seq(g, seq, f"col/{g.name}/{seq}/x/{g.rank}", pickle.dumps(tensor, protocol=5))
    _wait_all(f"col/{g.name}/{seq}/x", g.world_size)
    out = []
    for r in range(g.world_size):
        blob = _kv_wait(f"col/{g.name}/{seq}/x/{r}")
        out.append(pickle.loads(blob))
    return out


def _barrier_inner(g: _Group, seq: int):
    _put_seq(g, seq, f"col/{g.name}/{seq}/bar/{g.rank}", b"1")
    _wait_all(f"col/{g.name}/{seq}/bar", g.world_size)


def _tree_reduce(contribs: list, op: str):
    """Reduce a list of same-structure pytrees of numpy arrays."""
    import jax

    reducer = _REDUCERS[op]
    return jax.tree_util.tree_map(lambda *leaves: reducer(np.stack(leaves)), *contribs)
