"""Collective communication library.

Parity target: reference python/ray/util/collective/collective.py
(GroupManager:40, init_collective_group:123, allreduce:268; NCCL/GLOO
backends under util/collective/collective_group/).

TPU-native two-tier design (SURVEY §2.4/§2.5):
- **Device tier**: collective math between chips belongs INSIDE compiled XLA
  programs — `jax.lax.psum/all_gather/ppermute/all_to_all` over mesh axes
  (see ray_tpu.parallel) riding ICI. There is no NCCL-style out-of-band
  device group to manage, so this module doesn't wrap one.
- **Host tier** (this module): cross-process collectives for host data —
  gradient allreduce across TPU hosts (DCN), rendezvous/barriers for worker
  groups, weight broadcast — playing the role the reference's GLOO groups
  play.

Transport: the controller KV is used ONCE per group, as the address
rendezvous. Every collective then runs over DIRECT worker-to-worker RPC
connections in a ring — bandwidth-optimal ring allreduce (reduce-scatter +
all-gather, 2(W-1) steps moving ~2·data/W per link per step), ring
allgather and broadcast forwarding. Nothing flows through the controller,
so per-step gradient sync scales to large worlds instead of serializing
O(world^2) copies through one asyncio loop (round-3 verdict weakness).

Every rank calls init_collective_group(world_size, rank, group_name) first
(reference collective.py:123), then the collectives; calls are matched by a
per-group monotonically increasing sequence number.
"""

from __future__ import annotations

import pickle
import threading
import time
from collections import deque
from dataclasses import dataclass, field

import numpy as np

from ray_tpu import exceptions as exc
from ray_tpu._private import rpc, watchdog
from ray_tpu._private import tracing as _tracing
from ray_tpu._private.rtconfig import CONFIG
from ray_tpu._private.worker import global_worker

_DEFAULT_TIMEOUT = 120.0


def _op_timeout() -> float:
    """Per-op deadline (RT_COLLECTIVE_TIMEOUT_S; <=0 = module default).
    Every blocking recv inside an op is bounded by it, so a ring wedged on
    a sick peer aborts with CollectiveTimeoutError instead of hanging."""
    t = CONFIG.collective_timeout_s
    return float(t) if t and t > 0 else _DEFAULT_TIMEOUT


class ReduceOp:
    SUM = "sum"
    PRODUCT = "product"
    MIN = "min"
    MAX = "max"


_REDUCERS = {
    ReduceOp.SUM: lambda a, b: a + b,
    ReduceOp.PRODUCT: lambda a, b: a * b,
    ReduceOp.MIN: lambda a, b: np.minimum(a, b),
    ReduceOp.MAX: lambda a, b: np.maximum(a, b),
}


# --------------------------------------------------------------- transport
_inbox_cv = threading.Condition()
_inboxes: dict[tuple, deque] = {}  # (group, tag, src) -> messages


def _inbox_deliver(a: dict):
    """Runs on the worker's IO loop for every inbound col_msg push."""
    key = (a["group"], a["tag"], a["src"])
    with _inbox_cv:
        _inboxes.setdefault(key, deque()).append(a["blob"])
        _inbox_cv.notify_all()


def _inbox_recv(group: str, tag: str, src: int,
                timeout: float = _DEFAULT_TIMEOUT) -> bytes:
    key = (group, tag, src)
    deadline = time.monotonic() + timeout
    with _inbox_cv:
        while True:
            q = _inboxes.get(key)
            if q:
                blob = q.popleft()
                if not q:
                    del _inboxes[key]
                return blob
            rem = deadline - time.monotonic()
            if rem <= 0:
                raise TimeoutError(
                    f"collective recv timeout: group={group} tag={tag} src={src}")
            # Stall plane armed: wait in slices, ticking the beacon each
            # one — this block is BOUNDED by the op's own deadline, whose
            # expiry produces the far more actionable
            # CollectiveTimeoutError (it names the wedged peer), so the
            # generic per-task kill ladder must not win the race just
            # because RT_STALL_KILL_S < the op deadline. Unarmed (the
            # default): one full-duration wait, zero extra wakeups.
            if watchdog.is_armed():
                _inbox_cv.wait(min(rem, 0.25))
                watchdog.report_progress()
            else:
                _inbox_cv.wait(rem)


@dataclass
class _Group:
    name: str
    world_size: int
    rank: int
    seq: int = 0
    addrs: dict = field(default_factory=dict)  # rank -> (host, port)
    conns: dict = field(default_factory=dict)  # rank -> rpc.Connection
    # P2P counters, per peer and per direction — INDEPENDENT of the group
    # seq: p2p matches only (src, dst, nth-message), so an asymmetric
    # send/recv pattern must not desync the group's collective sequence
    # (round-2 advisor finding).
    p2p_sent: dict = field(default_factory=dict)
    p2p_rcvd: dict = field(default_factory=dict)


class GroupManager:
    """Per-process registry of collective groups (reference GroupManager,
    collective.py:40)."""

    def __init__(self):
        self._groups: dict[str, _Group] = {}

    def create(self, group_name: str, world_size: int, rank: int) -> _Group:
        if not (0 <= rank < world_size):
            raise ValueError(f"rank {rank} out of range for world_size {world_size}")
        g = _Group(group_name, world_size, rank)
        self._groups[group_name] = g
        return g

    def get(self, group_name: str) -> _Group:
        if group_name not in self._groups:
            raise ValueError(
                f"collective group {group_name!r} not initialized in this process; "
                f"call init_collective_group() first")
        return self._groups[group_name]

    def destroy(self, group_name: str):
        g = self._groups.pop(group_name, None)
        if g is not None:
            w = global_worker()
            for conn in g.conns.values():
                try:
                    w.io.run(conn.close(), timeout=5)
                except Exception:
                    pass
            # Drop this rank's address key: a later re-init of the same
            # group name must re-rendezvous against LIVE addresses, not
            # this incarnation's (possibly dead) ones.
            try:
                w.kv("del", ns="collective",
                     key=f"col/{group_name}/addr/{g.rank}")
            except Exception:
                pass


_manager = GroupManager()


def init_collective_group(world_size: int, rank: int, group_name: str = "default"):
    """Join this process to a named collective group: publish this rank's
    RPC address in the controller KV (the one controller round trip per
    group) and collect every peer's (reference init_collective_group:123)."""
    w = _worker()
    w.collective_msg_cb = _inbox_deliver
    # Drop any stale messages from a previous incarnation of this group
    # name in this process (re-init after destroy).
    with _inbox_cv:
        for k in [k for k in _inboxes if k[0] == group_name]:
            del _inboxes[k]
    g = _manager.create(group_name, world_size, rank)
    _kv_put(f"col/{group_name}/addr/{rank}",
            pickle.dumps(tuple(w.server_addr)))
    _wait_all(f"col/{group_name}/addr", world_size)
    for r in range(world_size):
        g.addrs[r] = pickle.loads(_kv_wait(f"col/{group_name}/addr/{r}"))
    return g


def init_prenegotiated_group(world_size: int, rank: int, addrs: dict,
                             group_name: str = "default",
                             connect: bool = False):
    """Join a group whose full rank->(host, port) address map was gathered
    ONCE by a coordinator and pushed to every member — the compiled-DAG
    model applied to collectives: membership is negotiated at compile
    time, like channels are, so joining does no controller KV publish and
    no rendezvous polling (init_collective_group's per-rank put + poll).
    Pipeline/tensor-parallel stages use this: the DAG driver collects each
    stage worker's RPC address at build time and every stage joins with
    one local call. `connect=True` additionally dials every peer now, so
    first-op latency (and the device-object plane's preference for
    established group links, device_store._collective_conn) doesn't wait
    on a lazy connect."""
    w = _worker()
    w.collective_msg_cb = _inbox_deliver
    with _inbox_cv:
        for k in [k for k in _inboxes if k[0] == group_name]:
            del _inboxes[k]
    amap = {int(r): tuple(a) for r, a in addrs.items()}
    if len(amap) != world_size or sorted(amap) != list(range(world_size)):
        raise ValueError(
            f"pre-negotiated group {group_name!r}: address map must cover "
            f"ranks 0..{world_size - 1} exactly (got {sorted(amap)})")
    g = _manager.create(group_name, world_size, rank)
    g.addrs = amap
    if connect:
        for r in range(world_size):
            if r != rank:
                _conn_to(g, r)
    return g


def destroy_collective_group(group_name: str = "default"):
    _manager.destroy(group_name)


def get_rank(group_name: str = "default") -> int:
    return _manager.get(group_name).rank


def get_collective_group_size(group_name: str = "default") -> int:
    return _manager.get(group_name).world_size


def _conn_to(g: _Group, rank: int):
    conn = g.conns.get(rank)
    if conn is None or conn.closed:
        conn = _worker().io.run(
            rpc.connect(*g.addrs[rank], timeout=10), timeout=30)
        g.conns[rank] = conn
    return conn


def _send_to(g: _Group, rank: int, tag: str, blob: bytes):
    _conn_to(g, rank).push_threadsafe(
        "col_msg", group=g.name, tag=tag, src=g.rank, blob=blob)


def _recv_step(g: _Group, op: str, tag: str, src: int) -> bytes:
    """One bounded ring/p2p receive. A deadline expiry names the op, the
    group, this rank, and the peer the recv was WAITING on — on a ring
    that peer (or someone upstream of it) is the wedged one. Each
    completed step ticks the stall watchdog's progress beacon: a long
    healthy collective is progress, not a stall."""
    try:
        blob = _inbox_recv(g.name, tag, src, timeout=_op_timeout())
    except TimeoutError:
        watchdog.record("collective_timeout", f"{op} {g.name} <- r{src}")
        raise exc.CollectiveTimeoutError(
            f"collective {op!r} timed out after {_op_timeout():.1f}s in "
            f"group {g.name!r} (rank {g.rank}/{g.world_size}, seq {g.seq}): "
            f"still waiting on peer rank {src} — it (or a rank upstream of "
            f"it on the ring) has stalled or died; set "
            f"RT_COLLECTIVE_TIMEOUT_S to tune this deadline") from None
    watchdog.report_progress()
    return blob


# ------------------------------------------------------------- collectives
def allreduce(tensor, op: str = ReduceOp.SUM, group_name: str = "default"):
    """Allreduce a numpy array (or pytree of arrays) across the group via
    ring reduce-scatter + ring all-gather. Returns the reduced value
    (functional — numpy arrays aren't views of device memory here, unlike
    the reference's in-place NCCL semantics)."""
    import jax

    g = _manager.get(group_name)
    g.seq += 1
    if g.world_size == 1:
        return tensor
    leaves, treedef = jax.tree_util.tree_flatten(tensor)
    arrs = [np.asarray(x) for x in leaves]
    # Traced task context (if any) spans the whole ring op — the 2(W-1)
    # steps' wall time is exactly the per-step gradient-sync cost.
    with _tracing.span("collective.allreduce", "collective",
                       {"group": group_name, "seq": g.seq,
                        "world": g.world_size}):
        reduced = _ring_allreduce(g, g.seq, arrs, _REDUCERS[op])
    return jax.tree_util.tree_unflatten(treedef, reduced)


def allgather(tensor, group_name: str = "default") -> list:
    """Returns [rank0_value, rank1_value, ...] via a ring (W-1 forwarding
    steps; each step every link carries one rank's value)."""
    g = _manager.get(group_name)
    g.seq += 1
    if g.world_size == 1:
        return [tensor]
    W, r, seq = g.world_size, g.rank, g.seq
    out: list = [None] * W
    out[r] = tensor
    nxt, prv = (r + 1) % W, (r - 1) % W
    carry = pickle.dumps(tensor, protocol=5)
    with _tracing.span("collective.allgather", "collective",
                       {"group": group_name, "seq": seq, "world": W}):
        for step in range(W - 1):
            _send_to(g, nxt, f"ag{seq}.{step}", carry)
            carry = _recv_step(g, "allgather", f"ag{seq}.{step}", prv)
            out[(r - 1 - step) % W] = pickle.loads(carry)
    return out


def broadcast(tensor, src_rank: int = 0, group_name: str = "default"):
    """Ring-forward from src: each rank receives from its predecessor and
    forwards to its successor (unless the successor is src)."""
    g = _manager.get(group_name)
    g.seq += 1
    if g.world_size == 1:
        return tensor
    W, r, seq = g.world_size, g.rank, g.seq
    nxt, prv = (r + 1) % W, (r - 1) % W
    tag = f"bc{seq}"
    with _tracing.span("collective.broadcast", "collective",
                       {"group": group_name, "seq": seq, "world": W}):
        if r == src_rank:
            _send_to(g, nxt, tag, pickle.dumps(tensor, protocol=5))
            return tensor
        blob = _recv_step(g, "broadcast", tag, prv)
        if nxt != src_rank:
            _send_to(g, nxt, tag, blob)
        return pickle.loads(blob)


def reducescatter(tensor, op: str = ReduceOp.SUM, group_name: str = "default"):
    """Reduce across the group, return this rank's 1/world_size slice along
    axis 0 (reference reducescatter)."""
    g = _manager.get(group_name)
    reduced = allreduce(tensor, op, group_name)
    chunks = np.array_split(np.asarray(reduced), g.world_size, axis=0)
    return chunks[g.rank]


def barrier(group_name: str = "default"):
    """Two token laps around the ring: after lap one every rank has entered;
    lap two releases them (a single lap would let rank src exit while the
    tail of the ring is still arriving)."""
    g = _manager.get(group_name)
    g.seq += 1
    if g.world_size == 1:
        return
    W, r, seq = g.world_size, g.rank, g.seq
    nxt, prv = (r + 1) % W, (r - 1) % W
    with _tracing.span("collective.barrier", "collective",
                       {"group": group_name, "seq": seq, "world": W}):
        for lap in range(2):
            tag = f"bar{seq}.{lap}"
            if r == 0:
                _send_to(g, nxt, tag, b"")
                _recv_step(g, "barrier", tag, prv)
            else:
                _recv_step(g, "barrier", tag, prv)
                _send_to(g, nxt, tag, b"")


def send(tensor, dst_rank: int, group_name: str = "default"):
    """P2P send over the direct connection (reference collective.send);
    matched by the per-(src,dst) message counter — deliberately NOT the
    group seq, so asymmetric p2p patterns can't desync the collectives."""
    g = _manager.get(group_name)
    n = g.p2p_sent[dst_rank] = g.p2p_sent.get(dst_rank, 0) + 1
    _send_to(g, dst_rank, f"p2p{n}", pickle.dumps(tensor, protocol=5))


def recv(src_rank: int, group_name: str = "default"):
    g = _manager.get(group_name)
    n = g.p2p_rcvd[src_rank] = g.p2p_rcvd.get(src_rank, 0) + 1
    return pickle.loads(_recv_step(g, "recv", f"p2p{n}", src_rank))


# ---------------------------------------------------------- ring allreduce
def _partition_leaves(arrs: list, w: int) -> list[list[int]]:
    """Contiguous, byte-balanced buckets of leaf indices (one per rank)."""
    sizes = [a.nbytes for a in arrs]
    total = sum(sizes) or 1
    target = total / w
    buckets: list[list[int]] = [[] for _ in range(w)]
    b, acc = 0, 0.0
    for i, sz in enumerate(sizes):
        buckets[b].append(i)
        acc += sz
        # advance once this bucket is full, keeping at least the remaining
        # leaves >= remaining buckets is NOT required (empty buckets ok)
        if acc >= target * (b + 1) and b < w - 1:
            b += 1
    return buckets


def _ring_allreduce(g: _Group, seq: int, arrs: list, reduce2) -> list:
    """Classic ring: W-1 reduce-scatter steps then W-1 all-gather steps.
    Buckets are contiguous groups of pytree leaves (byte-balanced), so
    mixed dtypes/shapes need no flat-buffer packing. At RS step t rank r
    sends bucket (r-t) mod W and reduces into bucket (r-t-1) mod W; after
    W-1 steps r owns fully-reduced bucket (r+1) mod W. AG step t forwards
    bucket (r+1-t) mod W."""
    W, r = g.world_size, g.rank
    buckets = _partition_leaves(arrs, W)
    acc: dict[int, list] = {b: [arrs[i] for i in idxs]
                            for b, idxs in enumerate(buckets)}
    nxt, prv = (r + 1) % W, (r - 1) % W
    for t in range(W - 1):
        sb, rb = (r - t) % W, (r - t - 1) % W
        _send_to(g, nxt, f"rs{seq}.{t}",
                 pickle.dumps(acc[sb], protocol=5))
        inc = pickle.loads(_recv_step(g, "allreduce", f"rs{seq}.{t}", prv))
        acc[rb] = [reduce2(a, b) for a, b in zip(acc[rb], inc)]
    carry = pickle.dumps(acc[(r + 1) % W], protocol=5)
    for t in range(W - 1):
        rb = (r - t) % W
        # Forward the raw blob received last step — re-pickling an already
        # serialized bucket at every hop would cost ~2.G.(W-2)/W extra
        # serialization work per allreduce.
        _send_to(g, nxt, f"ag{seq}.{t}", carry)
        carry = _recv_step(g, "allreduce", f"ag{seq}.{t}", prv)
        acc[rb] = pickle.loads(carry)
    out = [None] * len(arrs)
    for b, idxs in enumerate(buckets):
        for j, i in enumerate(idxs):
            out[i] = acc[b][j]
    return out


# ---------------------------------------------------------------- plumbing
def _worker():
    w = global_worker()
    if w is None:
        raise RuntimeError("ray_tpu.init() must be called before collectives")
    return w


def _kv_put(key: str, value: bytes):
    _worker().kv("put", ns="collective", key=key, value=value)


def _kv_get(key: str):
    return _worker().kv("get", ns="collective", key=key)["value"]


def _kv_wait(key: str, timeout: float = _DEFAULT_TIMEOUT,
             interval: float = 0.003) -> bytes:
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        v = _kv_get(key)
        if v is not None:
            return v
        time.sleep(interval)
    raise TimeoutError(f"collective timeout waiting for {key}")


def _wait_all(prefix: str, world_size: int, timeout: float = _DEFAULT_TIMEOUT):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        keys = _worker().kv("keys", ns="collective", prefix=prefix)["keys"]
        if len(keys) >= world_size:
            return
        time.sleep(0.003)
    raise TimeoutError(f"collective rendezvous timeout on {prefix}")
