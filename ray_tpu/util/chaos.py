"""Chaos/fault-injection tooling for tests and resilience drills.

Parity target: reference python/ray/_private/test_utils.py:1386
(ResourceKillerActor / get_and_run_resource_killer — periodically kill
nodes under a live workload). Driver-side here: the Cluster test fixture
owns the node subprocesses, so the killer thread drives kill/add cycles
through it.
"""

from __future__ import annotations

import logging
import random
import threading
import time
from typing import Optional

logger = logging.getLogger(__name__)


class NodeKiller:
    """Periodically kills a random non-head node (and optionally replaces
    it) while a workload runs.

        killer = NodeKiller(cluster, interval_s=1.0, replace=True)
        killer.start()
        ... run workload ...
        killer.stop()
        assert killer.kills > 0
    """

    def __init__(self, cluster, *, interval_s: float = 1.0,
                 replace: bool = True, max_kills: Optional[int] = None,
                 node_resources: Optional[dict] = None, seed: int = 0):
        self.cluster = cluster
        self.interval_s = interval_s
        self.replace = replace
        self.max_kills = max_kills
        self.node_resources = node_resources or {"num_cpus": 1}
        self.kills = 0
        self._rng = random.Random(seed)
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def start(self):
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="chaos-node-killer")
        self._thread.start()
        return self

    def _run(self):
        while not self._stop.is_set():
            if self._stop.wait(self.interval_s):
                return
            if self.max_kills is not None and self.kills >= self.max_kills:
                return
            victims = list(self.cluster.nodes)
            if not victims:
                continue
            victim = self._rng.choice(victims)
            try:
                self.cluster.remove_node(victim)
                self.kills += 1
                logger.warning("chaos: killed node %s", victim.node_id[:8])
            except Exception as e:
                logger.warning("chaos: kill failed: %r", e)
                continue
            if self.replace and not self._stop.is_set():
                try:
                    self.cluster.add_node(**self.node_resources)
                except Exception as e:
                    logger.warning("chaos: replace failed: %r", e)

    def stop(self):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=30)
