"""Distributed Queue backed by an async actor.

Parity target: reference python/ray/util/queue.py (Queue — an actor
wrapping asyncio.Queue; put/get with block/timeout, qsize/empty/full,
put_nowait/get_nowait, shutdown).
"""

from __future__ import annotations

import asyncio
from typing import Any, Optional

import ray_tpu


class Empty(Exception):
    pass


class Full(Exception):
    pass


class _QueueActor:
    def __init__(self, maxsize: int):
        self.q: asyncio.Queue = asyncio.Queue(maxsize=maxsize)

    async def put(self, item, timeout: Optional[float] = None) -> bool:
        if timeout is None:
            await self.q.put(item)
            return True
        try:
            await asyncio.wait_for(self.q.put(item), timeout)
            return True
        except asyncio.TimeoutError:
            return False

    async def get(self, timeout: Optional[float] = None):
        if timeout is None:
            return (True, await self.q.get())
        try:
            return (True, await asyncio.wait_for(self.q.get(), timeout))
        except asyncio.TimeoutError:
            return (False, None)

    async def put_nowait(self, item) -> bool:
        try:
            self.q.put_nowait(item)
            return True
        except asyncio.QueueFull:
            return False

    async def get_nowait(self):
        try:
            return (True, self.q.get_nowait())
        except asyncio.QueueEmpty:
            return (False, None)

    async def qsize(self) -> int:
        return self.q.qsize()

    async def empty(self) -> bool:
        return self.q.empty()

    async def full(self) -> bool:
        return self.q.full()


class Queue:
    """Driver/worker-side handle; picklable (ships the actor handle)."""

    def __init__(self, maxsize: int = 0, *, actor_options: Optional[dict] = None,
                 _actor=None):
        if _actor is not None:
            self.actor = _actor
            return
        opts = dict(actor_options or {})
        opts.setdefault("num_cpus", 0)
        opts.setdefault("max_concurrency", 64)
        self.actor = ray_tpu.remote(**opts)(_QueueActor).remote(maxsize)

    def put(self, item, block: bool = True, timeout: Optional[float] = None):
        if not block:
            ok = ray_tpu.get(self.actor.put_nowait.remote(item), timeout=30)
            if not ok:
                raise Full()
            return
        ok = ray_tpu.get(self.actor.put.remote(item, timeout),
                         timeout=None if timeout is None else timeout + 30)
        if not ok:
            raise Full()

    def get(self, block: bool = True, timeout: Optional[float] = None):
        if not block:
            ok, item = ray_tpu.get(self.actor.get_nowait.remote(), timeout=30)
            if not ok:
                raise Empty()
            return item
        ok, item = ray_tpu.get(self.actor.get.remote(timeout),
                               timeout=None if timeout is None else timeout + 30)
        if not ok:
            raise Empty()
        return item

    def put_nowait(self, item):
        self.put(item, block=False)

    def get_nowait(self):
        return self.get(block=False)

    def qsize(self) -> int:
        return ray_tpu.get(self.actor.qsize.remote(), timeout=30)

    def empty(self) -> bool:
        return ray_tpu.get(self.actor.empty.remote(), timeout=30)

    def full(self) -> bool:
        return ray_tpu.get(self.actor.full.remote(), timeout=30)

    def shutdown(self):
        try:
            ray_tpu.kill(self.actor)
        except Exception:
            pass

    def __reduce__(self):
        return (_rebuild_queue, (self.actor,))


def _rebuild_queue(actor) -> "Queue":
    return Queue(_actor=actor)
