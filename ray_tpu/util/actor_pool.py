"""ActorPool: load-balance work over a fixed set of actors.

Parity target: reference python/ray/util/actor_pool.py (ActorPool —
submit/get_next/get_next_unordered/map/map_unordered/has_next/has_free/
push/pop_idle).
"""

from __future__ import annotations

from typing import Any, Callable, Iterable, Iterator

import ray_tpu


class ActorPool:
    def __init__(self, actors: list):
        self._idle = list(actors)
        self._future_to_actor: dict = {}
        self._index_to_future: dict[int, Any] = {}
        self._next_task_index = 0
        self._next_return_index = 0
        self._pending_submits: list[tuple[Callable, Any]] = []

    def submit(self, fn: Callable, value):
        """fn(actor, value) -> ObjectRef; queued if no actor is idle."""
        if self._idle:
            actor = self._idle.pop()
            ref = fn(actor, value)
            self._future_to_actor[ref] = (self._next_task_index, actor)
            self._index_to_future[self._next_task_index] = ref
            self._next_task_index += 1
        else:
            self._pending_submits.append((fn, value))

    def has_next(self) -> bool:
        return bool(self._index_to_future) or bool(self._pending_submits)

    def has_free(self) -> bool:
        return bool(self._idle) and not self._pending_submits

    def _return_actor(self, actor):
        self._idle.append(actor)
        if self._pending_submits:
            fn, value = self._pending_submits.pop(0)
            self.submit(fn, value)

    def get_next(self, timeout: float | None = None):
        """Next result in SUBMISSION order."""
        if not self.has_next():
            raise StopIteration("no pending results")
        # Skip indices already consumed by get_next_unordered (mixed usage).
        while (self._next_return_index not in self._index_to_future
               and self._next_return_index < self._next_task_index):
            self._next_return_index += 1
        idx = self._next_return_index
        if idx not in self._index_to_future:
            raise StopIteration("no pending results")
        ref = self._index_to_future[idx]
        from ray_tpu.exceptions import GetTimeoutError

        try:
            out = ray_tpu.get(ref, timeout=timeout)
        except GetTimeoutError:
            raise  # task still running: bookkeeping stays intact
        except Exception:
            # Task COMPLETED with an error: the actor is free again.
            self._index_to_future.pop(idx, None)
            self._next_return_index += 1
            _i, actor = self._future_to_actor.pop(ref)
            self._return_actor(actor)
            raise
        self._index_to_future.pop(idx, None)
        self._next_return_index += 1
        _i, actor = self._future_to_actor.pop(ref)
        self._return_actor(actor)
        return out

    def get_next_unordered(self, timeout: float | None = None):
        """Next result in COMPLETION order."""
        if not self._future_to_actor:
            raise StopIteration("no pending results")
        done, _ = ray_tpu.wait(list(self._future_to_actor),
                               num_returns=1, timeout=timeout)
        if not done:
            raise TimeoutError("get_next_unordered timed out")
        ref = done[0]
        idx, actor = self._future_to_actor.pop(ref)
        self._index_to_future.pop(idx, None)
        if idx == self._next_return_index:
            self._next_return_index += 1
        self._return_actor(actor)
        return ray_tpu.get(ref, timeout=timeout)

    def map(self, fn: Callable, values: Iterable) -> Iterator:
        for v in values:
            self.submit(fn, v)
        while self.has_next():
            yield self.get_next()

    def map_unordered(self, fn: Callable, values: Iterable) -> Iterator:
        for v in values:
            self.submit(fn, v)
        while self.has_next():
            yield self.get_next_unordered()

    def push(self, actor):
        self._return_actor(actor)

    def pop_idle(self):
        return self._idle.pop() if self._idle else None
