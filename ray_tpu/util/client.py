"""Remote-driver ("Ray Client") surface.

Parity target: reference python/ray/util/client/ — a gRPC proxy that lets
a driver OUTSIDE the cluster run the full API, needed there because a
reference driver must colocate with a raylet. This framework's driver
never needs a local node agent: `ray_tpu.init(address=...)` already runs
the complete API from any machine that can reach the controller (the
worker registers as a remote client; leases, actor pipes, and object
fetches all ride ordinary connections). So the client mode here is a thin
alias with the reference's `ray.init("ray://host:port")` ergonomics:

    from ray_tpu.util.client import connect
    client = connect("host:6380")      # or ray_tpu.init(address=...)
    ...
    client.disconnect()
"""

from __future__ import annotations

from typing import Optional

import ray_tpu


class ClientContext:
    """Handle for a remote-driver session (reference ClientContext)."""

    def __init__(self, address: str):
        self.address = address
        self._connected = True

    def disconnect(self):
        if self._connected:
            self._connected = False
            ray_tpu.shutdown()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.disconnect()
        return False

    def __repr__(self):
        state = "connected" if self._connected else "disconnected"
        return f"ClientContext({self.address!r}, {state})"


def connect(address: str, namespace: str = "default",
            runtime_env: Optional[dict] = None) -> ClientContext:
    """Connect this process as a remote driver (reference
    ray.util.client.connect / ray.init("ray://...")). Accepts the
    "ray://host:port" scheme for drop-in familiarity."""
    if address.startswith("ray://"):
        address = address[len("ray://"):]
    ray_tpu.init(address=address, namespace=namespace,
                 runtime_env=runtime_env)
    return ClientContext(address)
