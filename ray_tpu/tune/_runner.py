"""The per-trial actor: hosts the user trainable.

Parity target: reference python/ray/tune/trainable/ — the controller talks
to one actor per live trial (tune_controller.py:666 step loop ->
_actor_to_trial futures). Function trainables run in a daemon thread and
communicate through the session queue; class trainables (reference
Trainable API: setup/step/save_checkpoint/load_checkpoint) are stepped by
the same loop so the controller sees one uniform next_result() interface.
"""

from __future__ import annotations

import os
import threading
import traceback
from typing import Optional

from ray_tpu.tune import _session


class TrialRunner:
    """NOT decorated: the controller wraps it with ray_tpu.remote(...) so
    per-trial resources can be attached."""

    def __init__(self, trainable, config: dict, trial_id: str, trial_dir: str,
                 restore_from: Optional[str] = None, incarnation: int = 0):
        from ray_tpu import storage

        storage.makedirs(trial_dir)
        self.sess = _session.init_session(trial_id, trial_dir, restore_from,
                                          incarnation)
        self.trainable = trainable
        self.config = config
        self._thread: Optional[threading.Thread] = None

    def start(self):
        if self._thread is not None:
            return
        self._thread = threading.Thread(target=self._run, daemon=True)
        self._thread.start()

    def _run(self):
        sess = self.sess
        try:
            if isinstance(self.trainable, type):
                self._run_class_trainable()
            else:
                out = self.trainable(self.config)
                if isinstance(out, dict):
                    sess.queue.put(("final", dict(out), None))
        except _session.StopTrial:
            pass
        except BaseException:  # noqa: BLE001 - report, don't kill the actor
            sess.queue.put(("error", traceback.format_exc(), None))
            return
        sess.queue.put(("done", None, None))

    def _run_class_trainable(self):
        """Reference Trainable class API: setup/step/save/load_checkpoint."""
        from ray_tpu.train.checkpoint import Checkpoint

        sess = self.sess
        t = self.trainable()
        if hasattr(t, "setup"):
            t.setup(self.config)
        if sess.restore_from and hasattr(t, "load_checkpoint"):
            # Materialize through the storage plane when the checkpoint
            # lives on a non-local backend; local dirs pass through as-is.
            with Checkpoint(sess.restore_from).as_directory() as d:
                t.load_checkpoint(d)
        while not sess.stopped.is_set():
            result = t.step()
            ckpt = None
            if hasattr(t, "save_checkpoint"):
                import tempfile

                with tempfile.TemporaryDirectory() as d:
                    t.save_checkpoint(d)
                    if os.listdir(d):
                        ckpt = Checkpoint(d)
                        sess.report(result, checkpoint=ckpt)
                        continue
            sess.report(result)

    def next_result(self, timeout: float = 10.0):
        """Block up to `timeout` for the next event. Returns (kind, payload,
        checkpoint_path) or None on timeout. kinds: report|final|error|done."""
        import queue as _q

        try:
            return self.sess.queue.get(timeout=timeout)
        except _q.Empty:
            return None

    def stop(self):
        """Ask the trainable to unwind at its next report()."""
        self.sess.stopped.set()
        # Unblock a report() currently waiting for the queue slot.
        try:
            self.sess.queue.get_nowait()
        except Exception:
            pass
        return True
