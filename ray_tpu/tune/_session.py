"""In-trial session: the bridge between user training code and the Tune
controller.

Parity target: reference python/ray/tune/trainable/function_trainable.py
(_StatusReporter / session.report) — the function trainable runs in its own
thread and hands results to the controller through a queue; report() blocks
until the controller-side consumer has taken the result, keeping iteration
cadence aligned with scheduler decisions.
"""

from __future__ import annotations

import queue
import threading
from typing import Optional

from ray_tpu import storage
from ray_tpu.train import checkpoint as ckpt_mod
from ray_tpu.train.checkpoint import Checkpoint

_session: Optional["_TuneSession"] = None
_lock = threading.Lock()


class StopTrial(BaseException):
    """Raised inside the trainable's thread to unwind when the controller
    stops the trial (BaseException so user `except Exception` can't eat it)."""


class _TuneSession:
    def __init__(self, trial_id: str, trial_dir: str,
                 restore_from: Optional[str], incarnation: int = 0):
        self.trial_id = trial_id
        self.trial_dir = trial_dir
        self.restore_from = restore_from
        # Which start of this trial we are (error restarts, PBT exploits):
        # checkpoint dirs are namespaced by it so a restarted trial can
        # never OVERWRITE an earlier incarnation's checkpoint — which a
        # PBT clone may have pinned as its restore source (pins prevent
        # deletion; unique names prevent overwrite).
        self.incarnation = incarnation
        self.queue: "queue.Queue" = queue.Queue(maxsize=1)
        self.stopped = threading.Event()
        self.iteration = 0
        self._ckpt_seq = 0

    def report(self, metrics: dict, checkpoint: Optional[Checkpoint] = None):
        if self.stopped.is_set():
            raise StopTrial()
        self.iteration += 1
        ckpt_path = None
        if checkpoint is not None:
            self._ckpt_seq += 1
            ckpt_path = storage.join(
                self.trial_dir,
                f"checkpoint_i{self.incarnation}_{self._ckpt_seq:06d}")
            if checkpoint.path != ckpt_path:
                # Through the storage seam: manifest-committed upload
                # (sync — tune cadence is controller-paced), then
                # keep-last-K retention. Pinned checkpoints (a PBT
                # clone's restore donor) survive retention.
                with checkpoint.as_directory() as src:
                    ckpt_mod.upload_directory(src, ckpt_path,
                                              step=self._ckpt_seq)
                from ray_tpu._private.rtconfig import CONFIG

                if CONFIG.ckpt_keep:
                    ckpt_mod.retention(self.trial_dir, CONFIG.ckpt_keep)
        metrics = dict(metrics)
        metrics.setdefault("training_iteration", self.iteration)
        self.queue.put(("report", metrics, ckpt_path))

    def get_checkpoint(self) -> Optional[Checkpoint]:
        if self.restore_from:
            return Checkpoint(self.restore_from)
        return None


def init_session(trial_id: str, trial_dir: str, restore_from: Optional[str],
                 incarnation: int = 0) -> _TuneSession:
    global _session
    with _lock:
        _session = _TuneSession(trial_id, trial_dir, restore_from,
                                incarnation)
        return _session


def get_session() -> _TuneSession:
    if _session is None:
        raise RuntimeError(
            "ray_tpu.tune.report()/get_checkpoint() must be called from "
            "inside a trial launched by Tuner.fit()")
    return _session


def report(metrics: dict, *, checkpoint: Optional[Checkpoint] = None):
    get_session().report(metrics, checkpoint)


def get_checkpoint() -> Optional[Checkpoint]:
    return get_session().get_checkpoint()
