"""Trial bookkeeping.

Parity target: reference python/ray/tune/experiment/trial.py (Trial status
machine PENDING/RUNNING/PAUSED/TERMINATED/ERROR).
"""

from __future__ import annotations

import uuid
from typing import Any, Optional

PENDING = "PENDING"
RUNNING = "RUNNING"
TERMINATED = "TERMINATED"
ERROR = "ERROR"


class Trial:
    def __init__(self, config: dict, trial_dir: str):
        self.trial_id = uuid.uuid4().hex[:8]
        self.config = config
        self.trial_dir = trial_dir
        self.status = PENDING
        self.runner = None  # actor handle while RUNNING
        self.last_result: Optional[dict] = None
        self.results: list[dict] = []
        self.checkpoint_path: Optional[str] = None
        self.restore_from: Optional[str] = None  # set by PBT exploit
        #: checkpoint dir this trial pinned as its restore source (PBT
        #: clone-from-donor / error restart); released by the controller
        #: once the trial checkpoints for itself or stops.
        self.pinned_source: Optional[str] = None
        #: how many times this trial has been started (error restarts, PBT
        #: exploits); namespaces checkpoint dirs so a restart never
        #: overwrites an earlier incarnation's (possibly pinned) checkpoint.
        self.incarnation = 0
        self.error: Optional[str] = None
        self.iteration = 0
        # scheduler scratch (e.g. ASHA rungs this trial has been recorded at)
        self.sched_state: dict[str, Any] = {}

    def metric(self, name: str, default=None):
        if self.last_result is None:
            return default
        return self.last_result.get(name, default)

    def __repr__(self):
        return f"Trial({self.trial_id}, {self.status}, it={self.iteration})"
