"""Tuner + the trial event-loop controller.

Parity target: reference python/ray/tune/tuner.py:43 (Tuner, fit:312),
tune/execution/tune_controller.py:68 (TuneController; step loop :666 —
start trials, collect actor futures, route results to scheduler/searcher,
stop/perturb/restart), tune/result_grid.py (ResultGrid).

Execution model: one actor per live trial hosting the trainable
(ray_tpu/tune/_runner.py); the controller's loop multiplexes
`next_result()` futures over ray_tpu.wait — the same shape as the
reference's _actor_to_trial future bookkeeping, minus the placement-group
indirection (trial resources ride the actor's own resource request).
"""

from __future__ import annotations

import logging
import os
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Any, Callable, Optional

import ray_tpu
from ray_tpu import storage
from ray_tpu.train import JaxTrainer, RunConfig
from ray_tpu.train import checkpoint as ckpt_mod
from ray_tpu.train.checkpoint import Checkpoint
from ray_tpu.tune import schedulers as sched_mod
from ray_tpu.tune._runner import TrialRunner
from ray_tpu.tune.search import BasicVariantGenerator
from ray_tpu.tune.trial import ERROR, PENDING, RUNNING, TERMINATED, Trial

logger = logging.getLogger(__name__)


@dataclass
class TuneConfig:
    """reference tune/tune_config.py."""

    metric: Optional[str] = None
    mode: Optional[str] = None  # "min" | "max"
    num_samples: int = 1
    scheduler: Optional[Any] = None
    #: a search.Searcher (e.g. TPESearcher): trials are then SUGGESTED
    #: sequentially from completed results instead of pre-sampled
    search_alg: Optional[Any] = None
    max_concurrent_trials: Optional[int] = None
    seed: Optional[int] = None
    resources_per_trial: Optional[dict] = None


@dataclass
class Result:
    """reference air/result.py Result."""

    metrics: Optional[dict]
    config: dict
    checkpoint: Optional[Checkpoint]
    error: Optional[str]
    path: str
    trial_id: str

    @property
    def best_checkpoint(self):
        return self.checkpoint


class ResultGrid:
    """reference tune/result_grid.py."""

    def __init__(self, results: list[Result], metric: Optional[str],
                 mode: Optional[str]):
        self._results = results
        self._metric, self._mode = metric, mode

    def __len__(self):
        return len(self._results)

    def __getitem__(self, i) -> Result:
        return self._results[i]

    def __iter__(self):
        return iter(self._results)

    @property
    def num_errors(self) -> int:
        return sum(1 for r in self._results if r.error is not None)

    def get_best_result(self, metric: Optional[str] = None,
                        mode: Optional[str] = None) -> Result:
        metric = metric or self._metric
        mode = mode or self._mode or "max"
        if metric is None:
            raise ValueError("pass metric= (or set TuneConfig.metric)")
        ok = [r for r in self._results
              if r.metrics is not None and metric in r.metrics]
        if not ok:
            raise RuntimeError("no trial reported metric " + metric)
        return (max if mode == "max" else min)(
            ok, key=lambda r: r.metrics[metric])

    def get_dataframe(self):
        import pandas as pd

        rows = []
        for r in self._results:
            row = dict(r.metrics or {})
            row["trial_id"] = r.trial_id
            for k, v in r.config.items():
                row[f"config/{k}"] = v
            rows.append(row)
        return pd.DataFrame(rows)


class TuneController:
    """Event loop over trial actors (reference tune_controller.py:68)."""

    WAIT_S = 3.0
    RESULT_TIMEOUT_S = 2.0

    def __init__(self, trainable: Callable, configs: list[dict],
                 tune_config: TuneConfig, run_config: RunConfig,
                 exp_dir: str, param_space: Optional[dict] = None,
                 trials: Optional[list] = None,
                 searcher_pre_observed: bool = False):
        self.trainable = trainable
        self.tc = tune_config
        self.rc = run_config
        self.exp_dir = exp_dir
        self.param_space = param_space or {}
        self.searcher = tune_config.search_alg
        if trials is not None:
            self.trials = trials  # Tuner.restore passes rebuilt trials
        else:
            self.trials = [Trial(cfg, "") for cfg in configs]
        for t in self.trials:
            t.trial_dir = storage.join(exp_dir, f"trial_{t.trial_id}")
        if self.searcher is not None:
            self.searcher.set_search_properties(
                tune_config.metric, tune_config.mode, self.param_space)
            # Feed restored finished trials back into the model — but ONLY
            # when the searcher did not arrive via the pickled tune_config
            # (Tuner.restore): that searcher's internal state already
            # contains these observations, and replaying them would
            # double-count each result and skew e.g. the TPE quantile
            # split. The replay exists for callers who wire a FRESH
            # searcher to restored trials.
            if not searcher_pre_observed:
                for t in self.trials:
                    if t.status == TERMINATED and t.last_result:
                        self.searcher.observe(t.config, t.last_result)
        self.scheduler = tune_config.scheduler or sched_mod.FIFOScheduler()
        self.scheduler.setup(tune_config.metric, tune_config.mode)
        self._futures: dict = {}  # next_result future -> (trial, runner)
        self._restarts: dict[str, int] = {}  # trial_id -> failure count

    # ----------------------------------------------------------- lifecycle
    def _remote_runner(self):
        res = dict(self.tc.resources_per_trial or {"CPU": 1})
        num_cpus = res.pop("CPU", 1)
        return ray_tpu.remote(num_cpus=num_cpus, resources=res or None,
                              max_concurrency=2)(TrialRunner)

    def _start(self, trial: Trial):
        runner_cls = self._remote_runner()
        trial.incarnation += 1
        trial.runner = runner_cls.remote(
            self.trainable, trial.config, trial.trial_id, trial.trial_dir,
            trial.restore_from, trial.incarnation - 1)
        trial.runner.start.remote()
        trial.status = RUNNING
        self._ask(trial)

    def _ask(self, trial: Trial):
        fut = trial.runner.next_result.remote(self.RESULT_TIMEOUT_S)
        self._futures[fut] = (trial, trial.runner)

    def _kill(self, trial: Trial):
        runner, trial.runner = trial.runner, None
        if runner is not None:
            try:
                runner.stop.remote()
                ray_tpu.kill(runner)
            except Exception:
                pass

    def _stop_trial(self, trial: Trial, status: str = TERMINATED,
                    error: Optional[str] = None):
        trial.status = status
        trial.error = error
        self._kill(trial)
        self._release_restore_pin(trial)
        self.scheduler.on_trial_complete(self, trial)
        if self.searcher is not None:
            self.searcher.on_trial_complete(trial.trial_id, trial.last_result)
        try:
            self.save_experiment_state()
        except Exception:
            logger.exception("tune: experiment-state save failed")

    # --------------------------------------------------- checkpoint pinning
    # A trial restoring from a checkpoint it does not own (PBT exploit
    # clones from a donor; error-restarts re-read the trial's own last
    # dir) must keep that dir alive: the donor's retention/GC or a later
    # overwrite would otherwise corrupt the clone's restore source. Pins
    # are refcount marker files on the storage backend — visible to the
    # donor's session process — released once the trial has written a
    # checkpoint of its own (or stopped).
    def _pin_restore_source(self, trial: Trial, path: Optional[str]):
        self._release_restore_pin(trial)
        trial.restore_from = path
        if path:
            try:
                ckpt_mod.pin(path, owner=f"trial-{trial.trial_id}")
                trial.pinned_source = path
            except Exception:
                logger.exception("tune: pinning %s failed", path)

    def _release_restore_pin(self, trial: Trial):
        if trial.pinned_source:
            ckpt_mod.unpin(trial.pinned_source,
                           owner=f"trial-{trial.trial_id}")
            trial.pinned_source = None

    def exploit(self, trial: Trial, donor: Trial, new_config: dict):
        """PBT: restart `trial` from donor's checkpoint with a perturbed
        config (reference pbt.py _exploit:405)."""
        logger.info("tune: trial %s exploits %s", trial.trial_id, donor.trial_id)
        self._kill(trial)
        trial.config = new_config
        self._pin_restore_source(trial, donor.checkpoint_path)
        self._start(trial)

    # ----------------------------------------------------- experiment state
    def save_experiment_state(self):
        """Durable trial table (reference experiment_state-*.json written by
        the TuneController): enough to Tuner.restore() an interrupted
        experiment — finished trials keep results, unfinished ones re-run
        from their last checkpoint."""
        import cloudpickle

        state = {
            "num_samples": self.tc.num_samples,
            "metric": self.tc.metric,
            "mode": self.tc.mode,
            # Full configs ride pickled so restore keeps the searcher,
            # scheduler, concurrency cap, and failure policy.
            "tune_config": cloudpickle.dumps(self.tc).hex(),
            "run_config": cloudpickle.dumps(self.rc).hex(),
            "param_space": cloudpickle.dumps(self.param_space).hex(),
            "trainable": cloudpickle.dumps(self.trainable).hex(),
            "trials": [{
                "trial_id": t.trial_id,
                "config": cloudpickle.dumps(t.config).hex(),
                "status": t.status,
                "last_result": t.last_result,
                "checkpoint_path": t.checkpoint_path,
                "error": t.error,
                "iteration": t.iteration,
            } for t in self.trials],
        }
        import json

        def _default(o):
            # User metrics are full of numpy scalars on this stack; a
            # TypeError here would silently freeze the durable state.
            try:
                return float(o)
            except (TypeError, ValueError):
                return repr(o)

        # storage.put is atomic on every backend — the experiment state
        # file is either the old or the new version, never torn.
        storage.put(storage.join(self.exp_dir, "experiment_state.json"),
                    json.dumps(state, default=_default).encode())

    def _maybe_suggest(self) -> Optional[Trial]:
        """Searcher-driven trial creation (sequential; reference
        SearchGenerator)."""
        if self.searcher is None or len(self.trials) >= self.tc.num_samples:
            return None
        t = Trial({}, "")
        cfg = self.searcher.suggest(t.trial_id)
        if cfg is None:
            return None
        t.config = cfg
        t.trial_dir = storage.join(self.exp_dir, f"trial_{t.trial_id}")
        self.trials.append(t)
        return t

    # ---------------------------------------------------------- event loop
    def run(self) -> list[Trial]:
        # Restored TERMINATED/errored-out trials are not re-queued.
        pending = deque(t for t in self.trials if t.status == PENDING)
        if self.tc.max_concurrent_trials:
            limit = self.tc.max_concurrent_trials
        elif self.searcher is not None:
            # Searcher-driven runs MUST stay bounded or every sample is
            # suggested before any result lands and the model never sees
            # an observation (TPE degenerates to pure random). Default to
            # the searcher's startup width.
            limit = max(1, getattr(self.searcher, "n_startup", 4) or 4)
        else:
            limit = max(1, len(self.trials))
        while True:
            running = [t for t in self.trials if t.status == RUNNING]
            while pending and len(running) < limit:
                t = pending.popleft()
                self._start(t)
                running.append(t)
            while self.searcher is not None and len(running) < limit:
                t = self._maybe_suggest()
                if t is None:
                    break
                self._start(t)
                running.append(t)
            if not running and not pending:
                break
            if not self._futures:
                time.sleep(0.05)
                continue
            done, _ = ray_tpu.wait(list(self._futures), num_returns=1,
                                   timeout=self.WAIT_S)
            for fut in done:
                trial, runner = self._futures.pop(fut)
                if trial.runner is not runner:
                    continue  # stale future from a pre-exploit incarnation
                try:
                    event = ray_tpu.get(fut, timeout=5)
                except Exception as e:  # actor died (or was killed)
                    if trial.status == RUNNING:
                        self._on_trial_error(trial, repr(e))
                    continue
                self._on_event(trial, event)
        return self.trials

    def _on_event(self, trial: Trial, event):
        if event is None:  # poll timeout: keep listening
            self._ask(trial)
            return
        kind, payload, ckpt_path = event
        if kind in ("report", "final"):
            metrics = dict(payload)
            trial.last_result = metrics
            trial.results.append(metrics)
            trial.iteration = metrics.get("training_iteration", trial.iteration)
            if ckpt_path:
                trial.checkpoint_path = ckpt_path
                # The trial now owns a durable checkpoint of its own: the
                # borrowed restore source (if any) can be collected.
                self._release_restore_pin(trial)
            if kind == "final":
                self._stop_trial(trial)
                return
            if self._hit_stop_criteria(metrics):
                self._stop_trial(trial)
                return
            runner_before = trial.runner
            decision = self.scheduler.on_trial_result(self, trial, metrics)
            if trial.runner is not runner_before:
                # Scheduler restarted the trial (PBT exploit): _start
                # already enqueued the new incarnation's poller — asking
                # again would double-poll and reorder reports.
                return
            if decision == sched_mod.STOP:
                self._stop_trial(trial)
            else:
                self._ask(trial)
        elif kind == "done":
            if trial.status == RUNNING:
                self._stop_trial(trial)
        elif kind == "error":
            self._on_trial_error(trial, payload)

    def _on_trial_error(self, trial: Trial, err: str):
        n = self._restarts.get(trial.trial_id, 0)
        maxf = self.rc.failure_config.max_failures
        if maxf == -1 or n < maxf:
            self._restarts[trial.trial_id] = n + 1
            logger.warning("tune: trial %s failed (%d/%s), restarting",
                           trial.trial_id, n + 1, maxf)
            self._kill(trial)
            self._pin_restore_source(trial, trial.checkpoint_path)
            self._start(trial)
        else:
            logger.error("tune: trial %s failed:\n%s", trial.trial_id, err)
            self._stop_trial(trial, status=ERROR, error=err)

    def _hit_stop_criteria(self, metrics: dict) -> bool:
        stop = getattr(self.rc, "stop", None)
        if not stop:
            return False
        return any(metrics.get(k) is not None and metrics[k] >= v
                   for k, v in stop.items())


def _trainable_from_trainer(trainer: JaxTrainer) -> Callable:
    """Run a JaxTrainer as a trial (reference base_trainer.py:651 wraps
    every Trainer into a Tune trial; param_space["train_loop_config"]
    overrides merge into the trainer's config)."""

    def _fit_trial(config):
        import dataclasses

        from ray_tpu.tune import _session

        cfg = dict(trainer._config or {})
        cfg.update(config.get("train_loop_config", config))
        sess = _session.get_session()
        run_cfg = dataclasses.replace(
            trainer._run_config, storage_path=os.path.join(
                sess.trial_dir, "train"), name=None)
        t = JaxTrainer(trainer._train_fn, train_loop_config=cfg,
                       scaling_config=trainer._scaling,
                       run_config=run_cfg, datasets=trainer._datasets)
        res = t.fit()
        sess.report(dict(res.metrics or {}),
                    checkpoint=res.checkpoint)

    return _fit_trial


class Tuner:
    """reference tune/tuner.py:43."""

    def __init__(self, trainable, *, param_space: Optional[dict] = None,
                 tune_config: Optional[TuneConfig] = None,
                 run_config: Optional[RunConfig] = None,
                 _restored_trials: Optional[list] = None,
                 _exp_dir: Optional[str] = None):
        if isinstance(trainable, JaxTrainer):
            trainable = _trainable_from_trainer(trainable)
        self._trainable = trainable
        self._param_space = param_space or {}
        self._tune_config = tune_config or TuneConfig()
        self._run_config = run_config or RunConfig()
        self._restored_trials = _restored_trials
        self._exp_dir = _exp_dir
        # True when restore() unpickled the tune_config: its searcher's
        # state already includes every finished trial's observation.
        self._searcher_from_pickle = False

    @classmethod
    def restore(cls, path: str, trainable=None) -> "Tuner":
        """Resume an interrupted experiment from its directory (reference
        tuner.py Tuner.restore): finished trials keep their results;
        unfinished/errored trials re-run from their last checkpoint."""
        import json

        import cloudpickle

        state = json.loads(storage.get_bytes(
            storage.join(path, "experiment_state.json")))
        if trainable is None:
            trainable = cloudpickle.loads(
                bytes.fromhex(state["trainable"]))
        elif isinstance(trainable, JaxTrainer):
            trainable = _trainable_from_trainer(trainable)
        param_space = cloudpickle.loads(bytes.fromhex(state["param_space"]))
        trials = []
        for ts in state["trials"]:
            t = Trial(cloudpickle.loads(bytes.fromhex(ts["config"])), "")
            t.trial_id = ts["trial_id"]
            t.last_result = ts["last_result"]
            t.checkpoint_path = ts["checkpoint_path"]
            t.iteration = ts.get("iteration", 0)
            if ts["status"] == TERMINATED:
                t.status = TERMINATED  # keep the result; don't re-run
            else:
                # RUNNING (interrupted) / PENDING / ERROR: re-run, resuming
                # from the last checkpoint when one exists.
                t.status = PENDING
                t.restore_from = ts["checkpoint_path"]
            trials.append(t)
        if state.get("tune_config"):
            tc = cloudpickle.loads(bytes.fromhex(state["tune_config"]))
        else:
            tc = TuneConfig(metric=state.get("metric"),
                            mode=state.get("mode"),
                            num_samples=state.get("num_samples", len(trials)))
        rc = (cloudpickle.loads(bytes.fromhex(state["run_config"]))
              if state.get("run_config") else RunConfig())
        tuner = cls(trainable, param_space=param_space, tune_config=tc,
                    run_config=rc, _restored_trials=trials,
                    _exp_dir=path)
        tuner._searcher_from_pickle = bool(state.get("tune_config"))
        return tuner

    def fit(self) -> ResultGrid:
        tc = self._tune_config
        if self._exp_dir is not None:
            exp_dir = self._exp_dir
        else:
            name = self._run_config.name or f"tune_{int(time.time())}"
            exp_dir = storage.join(self._run_config.resolved_storage(), name)
        storage.makedirs(exp_dir)
        if self._restored_trials is not None:
            configs = []
        elif tc.search_alg is not None:
            configs = []  # suggested live by the searcher
        else:
            configs = BasicVariantGenerator(tc.seed).generate(
                self._param_space, tc.num_samples)
        controller = TuneController(self._trainable, configs, tc,
                                    self._run_config, exp_dir,
                                    param_space=self._param_space,
                                    trials=self._restored_trials,
                                    searcher_pre_observed=self._searcher_from_pickle)
        controller.save_experiment_state()
        trials = controller.run()
        results = [
            Result(metrics=t.last_result, config=t.config,
                   checkpoint=Checkpoint(t.checkpoint_path)
                   if t.checkpoint_path else None,
                   error=t.error, path=t.trial_dir, trial_id=t.trial_id)
            for t in trials
        ]
        return ResultGrid(results, tc.metric, tc.mode)
