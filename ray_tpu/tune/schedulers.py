"""Trial schedulers: FIFO, ASHA, PBT.

Parity target: reference python/ray/tune/schedulers/trial_scheduler.py
(CONTINUE/STOP decisions), async_hyperband.py (AsyncHyperBandScheduler /
ASHA — rungs at grace_period * rf^k, cutoff at the top 1/rf quantile), and
pbt.py (PopulationBasedTraining — exploit top quantile + explore by
perturbing hyperparams, pbt.py:405 _exploit).
"""

from __future__ import annotations

import random
from typing import Optional

CONTINUE = "CONTINUE"
STOP = "STOP"


class FIFOScheduler:
    """Run every trial to completion (reference trial_scheduler.py:94)."""

    def setup(self, metric: Optional[str], mode: Optional[str]):
        self.metric, self.mode = metric, mode

    def on_trial_result(self, controller, trial, result) -> str:
        return CONTINUE

    def on_trial_complete(self, controller, trial):
        pass


class ASHAScheduler(FIFOScheduler):
    """Async successive halving (reference async_hyperband.py:343 _Bracket:
    on_result records the metric at the highest rung <= t and stops the
    trial if it falls below the rung's top-1/rf cutoff)."""

    def __init__(self, time_attr: str = "training_iteration",
                 metric: Optional[str] = None, mode: Optional[str] = None,
                 max_t: int = 100, grace_period: int = 1,
                 reduction_factor: float = 4):
        self.time_attr = time_attr
        self.metric, self.mode = metric, mode
        self.max_t = max_t
        self.grace_period = grace_period
        self.rf = reduction_factor
        # rung milestones, ascending: grace, grace*rf, grace*rf^2, ... < max_t
        self.rungs: list[float] = []
        r = grace_period
        while r < max_t:
            self.rungs.append(r)
            r *= reduction_factor
        # rung value -> list of recorded metric values (in +is-better units)
        self._recorded: dict[float, list[float]] = {r: [] for r in self.rungs}

    def setup(self, metric, mode):
        self.metric = self.metric or metric
        self.mode = self.mode or mode or "max"

    def _score(self, result) -> Optional[float]:
        v = result.get(self.metric)
        if v is None:
            return None
        return float(v) if self.mode == "max" else -float(v)

    def on_trial_result(self, controller, trial, result) -> str:
        t = result.get(self.time_attr, 0)
        if t >= self.max_t:
            return STOP
        score = self._score(result)
        if score is None:
            return CONTINUE
        decision = CONTINUE
        seen = trial.sched_state.setdefault("asha_rungs", set())
        for rung in self.rungs:
            if t < rung or rung in seen:
                continue
            seen.add(rung)
            recorded = self._recorded[rung]
            recorded.append(score)
            # Cutoff: top 1/rf of everything recorded at this rung so far.
            if len(recorded) >= self.rf:
                k = max(1, int(len(recorded) / self.rf))
                cutoff = sorted(recorded, reverse=True)[k - 1]
                if score < cutoff:
                    decision = STOP
        return decision


class PopulationBasedTraining(FIFOScheduler):
    """PBT: at each perturbation_interval, bottom-quantile trials clone the
    config+checkpoint of a top-quantile trial and perturb hyperparams
    (reference pbt.py _exploit:405 / _explore:88)."""

    def __init__(self, time_attr: str = "training_iteration",
                 metric: Optional[str] = None, mode: Optional[str] = None,
                 perturbation_interval: int = 5,
                 hyperparam_mutations: Optional[dict] = None,
                 quantile_fraction: float = 0.25,
                 resample_probability: float = 0.25,
                 seed: Optional[int] = None):
        self.time_attr = time_attr
        self.metric, self.mode = metric, mode
        self.interval = perturbation_interval
        self.mutations = hyperparam_mutations or {}
        self.quantile = quantile_fraction
        self.resample_prob = resample_probability
        self._rng = random.Random(seed)

    def setup(self, metric, mode):
        self.metric = self.metric or metric
        self.mode = self.mode or mode or "max"

    def _score(self, trial) -> Optional[float]:
        v = trial.metric(self.metric)
        if v is None:
            return None
        return float(v) if self.mode == "max" else -float(v)

    def _explore(self, config: dict) -> dict:
        """Perturb mutated hyperparams *1.2/*0.8 or resample (reference
        pbt.py _explore:88)."""
        from ray_tpu.tune.search import Domain

        out = dict(config)
        for key, spec in self.mutations.items():
            if self._rng.random() < self.resample_prob:
                if isinstance(spec, Domain):
                    out[key] = spec.sample(self._rng)
                elif isinstance(spec, list):
                    out[key] = self._rng.choice(spec)
                elif callable(spec):
                    out[key] = spec()
            elif isinstance(out.get(key), (int, float)):
                factor = 1.2 if self._rng.random() > 0.5 else 0.8
                out[key] = type(out[key])(out[key] * factor)
            elif isinstance(spec, list) and out.get(key) in spec:
                # shift to a neighboring value
                i = spec.index(out[key])
                out[key] = spec[max(0, min(len(spec) - 1,
                                           i + self._rng.choice((-1, 1))))]
        return out

    def on_trial_result(self, controller, trial, result) -> str:
        t = result.get(self.time_attr, 0)
        last = trial.sched_state.get("pbt_last_perturb", 0)
        if t - last < self.interval:
            return CONTINUE
        trial.sched_state["pbt_last_perturb"] = t
        peers = [tr for tr in controller.trials
                 if self._score(tr) is not None]
        if len(peers) < 2:
            return CONTINUE
        ranked = sorted(peers, key=self._score, reverse=True)
        k = max(1, int(len(ranked) * self.quantile))
        top, bottom = ranked[:k], ranked[-k:]
        if trial not in bottom or trial in top:
            return CONTINUE
        donor = self._rng.choice(top)
        if donor.checkpoint_path is None:
            return CONTINUE
        new_config = self._explore(donor.config)
        controller.exploit(trial, donor, new_config)
        return CONTINUE  # controller restarts the trial; no stop decision

    def on_trial_complete(self, controller, trial):
        pass
