"""Trial schedulers: FIFO, ASHA, PBT.

Parity target: reference python/ray/tune/schedulers/trial_scheduler.py
(CONTINUE/STOP decisions), async_hyperband.py (AsyncHyperBandScheduler /
ASHA — rungs at grace_period * rf^k, cutoff at the top 1/rf quantile), and
pbt.py (PopulationBasedTraining — exploit top quantile + explore by
perturbing hyperparams, pbt.py:405 _exploit).
"""

from __future__ import annotations

import random
from typing import Optional

CONTINUE = "CONTINUE"
STOP = "STOP"


class FIFOScheduler:
    """Run every trial to completion (reference trial_scheduler.py:94)."""

    metric: Optional[str] = None
    mode: Optional[str] = None

    def setup(self, metric: Optional[str], mode: Optional[str]):
        """Adopt the TuneConfig metric/mode unless the scheduler was built
        with its own (shared by every metric-driven scheduler below)."""
        self.metric = getattr(self, "metric", None) or metric
        self.mode = getattr(self, "mode", None) or mode or "max"

    def _score(self, result) -> Optional[float]:
        """Result's metric in +is-better units (None if absent)."""
        v = result.get(self.metric)
        if v is None:
            return None
        return float(v) if self.mode == "max" else -float(v)

    def on_trial_result(self, controller, trial, result) -> str:
        return CONTINUE

    def on_trial_complete(self, controller, trial):
        pass


class ASHAScheduler(FIFOScheduler):
    """Async successive halving (reference async_hyperband.py:343 _Bracket:
    on_result records the metric at the highest rung <= t and stops the
    trial if it falls below the rung's top-1/rf cutoff)."""

    def __init__(self, time_attr: str = "training_iteration",
                 metric: Optional[str] = None, mode: Optional[str] = None,
                 max_t: int = 100, grace_period: int = 1,
                 reduction_factor: float = 4):
        self.time_attr = time_attr
        self.metric, self.mode = metric, mode
        self.max_t = max_t
        if reduction_factor <= 1:
            raise ValueError("reduction_factor must be > 1")
        self.grace_period = grace_period
        self.rf = reduction_factor
        # rung milestones, ascending: grace, grace*rf, grace*rf^2, ... < max_t
        self.rungs: list[float] = []
        r = grace_period
        while r < max_t:
            self.rungs.append(r)
            r *= reduction_factor
        # rung value -> list of recorded metric values (in +is-better units)
        self._recorded: dict[float, list[float]] = {r: [] for r in self.rungs}


    def on_trial_result(self, controller, trial, result) -> str:
        t = result.get(self.time_attr, 0)
        if t >= self.max_t:
            return STOP
        score = self._score(result)
        if score is None:
            return CONTINUE
        decision = CONTINUE
        seen = trial.sched_state.setdefault("asha_rungs", set())
        for rung in self.rungs:
            if t < rung or rung in seen:
                continue
            seen.add(rung)
            recorded = self._recorded[rung]
            recorded.append(score)
            # Cutoff: top 1/rf of everything recorded at this rung so far.
            if len(recorded) >= self.rf:
                k = max(1, int(len(recorded) / self.rf))
                cutoff = sorted(recorded, reverse=True)[k - 1]
                if score < cutoff:
                    decision = STOP
        return decision


class PopulationBasedTraining(FIFOScheduler):
    """PBT: at each perturbation_interval, bottom-quantile trials clone the
    config+checkpoint of a top-quantile trial and perturb hyperparams
    (reference pbt.py _exploit:405 / _explore:88)."""

    def __init__(self, time_attr: str = "training_iteration",
                 metric: Optional[str] = None, mode: Optional[str] = None,
                 perturbation_interval: int = 5,
                 hyperparam_mutations: Optional[dict] = None,
                 quantile_fraction: float = 0.25,
                 resample_probability: float = 0.25,
                 seed: Optional[int] = None):
        self.time_attr = time_attr
        self.metric, self.mode = metric, mode
        self.interval = perturbation_interval
        self.mutations = hyperparam_mutations or {}
        self.quantile = quantile_fraction
        self.resample_prob = resample_probability
        self._rng = random.Random(seed)

    def setup(self, metric, mode):
        self.metric = self.metric or metric
        self.mode = self.mode or mode or "max"

    def _score(self, trial) -> Optional[float]:
        v = trial.metric(self.metric)
        if v is None:
            return None
        return float(v) if self.mode == "max" else -float(v)

    def _explore(self, config: dict) -> dict:
        """Perturb mutated hyperparams *1.2/*0.8 or resample (reference
        pbt.py _explore:88)."""
        from ray_tpu.tune.search import Domain

        out = dict(config)
        for key, spec in self.mutations.items():
            if self._rng.random() < self.resample_prob:
                if isinstance(spec, Domain):
                    out[key] = spec.sample(self._rng)
                elif isinstance(spec, list):
                    out[key] = self._rng.choice(spec)
                elif callable(spec):
                    out[key] = spec()
            elif isinstance(out.get(key), (int, float)):
                factor = 1.2 if self._rng.random() > 0.5 else 0.8
                out[key] = type(out[key])(out[key] * factor)
            elif isinstance(spec, list) and out.get(key) in spec:
                # shift to a neighboring value
                i = spec.index(out[key])
                out[key] = spec[max(0, min(len(spec) - 1,
                                           i + self._rng.choice((-1, 1))))]
        return out

    def on_trial_result(self, controller, trial, result) -> str:
        t = result.get(self.time_attr, 0)
        last = trial.sched_state.get("pbt_last_perturb", 0)
        if t - last < self.interval:
            return CONTINUE
        trial.sched_state["pbt_last_perturb"] = t
        peers = [tr for tr in controller.trials
                 if self._score(tr) is not None]
        if len(peers) < 2:
            return CONTINUE
        ranked = sorted(peers, key=self._score, reverse=True)
        k = max(1, int(len(ranked) * self.quantile))
        top, bottom = ranked[:k], ranked[-k:]
        if trial not in bottom or trial in top:
            return CONTINUE
        donor = self._rng.choice(top)
        if donor.checkpoint_path is None:
            return CONTINUE
        new_config = self._explore(donor.config)
        controller.exploit(trial, donor, new_config)
        return CONTINUE  # controller restarts the trial; no stop decision

    def on_trial_complete(self, controller, trial):
        pass


class MedianStoppingRule(FIFOScheduler):
    """Stop a trial whose best result so far is worse than the median of the
    running averages of other trials at the same step (reference
    tune/schedulers/median_stopping_rule.py: MedianStoppingRule)."""

    def __init__(self, time_attr: str = "training_iteration",
                 metric: Optional[str] = None, mode: Optional[str] = None,
                 grace_period: int = 1, min_samples_required: int = 3):
        self.time_attr = time_attr
        self.metric, self.mode = metric, mode
        self.grace_period = grace_period
        self.min_samples_required = min_samples_required
        # trial id -> list of scores (in +is-better units)
        self._scores: dict = {}


    def on_trial_result(self, controller, trial, result) -> str:
        t = result.get(self.time_attr, 0)
        score = self._score(result)
        if score is None:
            return CONTINUE
        self._scores.setdefault(trial.trial_id, []).append(score)
        if t < self.grace_period:
            return CONTINUE
        # Compare against other trials' running averages truncated to the
        # same REPORT COUNT as this trial — all-time averages would judge
        # late starters against finished trials' full runs, and slicing by
        # the raw time_attr value breaks for non-unit attrs like
        # timesteps_total (reference: median of running averages at the
        # same time step).
        upto = len(self._scores[trial.trial_id])
        others = [vals[:upto] for tid, vals in self._scores.items()
                  if tid != trial.trial_id and vals]
        if len(others) < self.min_samples_required:
            return CONTINUE
        medians = sorted(sum(vals) / len(vals) for vals in others)
        median = medians[len(medians) // 2]
        best = max(self._scores[trial.trial_id])
        return STOP if best < median else CONTINUE


class HyperBandScheduler(FIFOScheduler):
    """Synchronous HyperBand-style banding (reference
    tune/schedulers/hyperband.py, simplified to a single bracket):
    successive halving at milestones max_t/rf^k — at each milestone the
    bottom (1 - 1/rf) fraction of trials that reported there stop."""

    def __init__(self, time_attr: str = "training_iteration",
                 metric: Optional[str] = None, mode: Optional[str] = None,
                 max_t: int = 81, reduction_factor: float = 3):
        self.time_attr = time_attr
        self.metric, self.mode = metric, mode
        if reduction_factor <= 1:
            raise ValueError("reduction_factor must be > 1")
        self.max_t = max_t
        self.rf = reduction_factor
        milestones = []
        t = max_t
        while t >= 1:
            milestones.append(int(t))
            t /= reduction_factor
        self.milestones = sorted(set(milestones))[:-1]  # drop max_t itself
        self._recorded: dict[int, list[float]] = {m: [] for m in self.milestones}
        # milestone -> {trial_id: score}: cutoffs are re-evaluated on every
        # later report, so a bad trial that crossed a milestone before its
        # peers recorded there still gets halved once they do.
        self._at: dict[int, dict] = {m: {} for m in self.milestones}


    def on_trial_result(self, controller, trial, result) -> str:
        t = result.get(self.time_attr, 0)
        if t >= self.max_t:
            return STOP
        score = self._score(result)
        if score is None:
            return CONTINUE
        seen = trial.sched_state.setdefault("hb_milestones", set())
        for m in self.milestones:
            if t >= m and m not in seen:
                seen.add(m)
                self._recorded[m].append(score)
                self._at[m][trial.trial_id] = score
        # Judge ONLY at the highest crossed milestone: a stale low-rung
        # cutoff must not retroactively kill a trial that already survived
        # (and improved past) higher rungs.
        crossed = [m for m in self.milestones if t >= m]
        if not crossed:
            return CONTINUE
        m = crossed[-1]
        rec = self._recorded[m]
        if len(rec) >= self.rf:
            keep = max(1, int(len(rec) / self.rf))
            cutoff = sorted(rec, reverse=True)[keep - 1]
            if self._at[m][trial.trial_id] < cutoff:
                return STOP
        return CONTINUE
