"""ray_tpu.tune — distributed hyperparameter tuning.

Parity target: reference python/ray/tune (Tuner/TuneConfig/ResultGrid,
search spaces, ASHA/PBT schedulers). The hyperparameter axis of SURVEY
§2.4's parallelism strategies: trials are actors scheduled like any other
workload, so tuning composes with training/PGs/FT for free.
"""

from ray_tpu.tune._session import get_checkpoint, report
from ray_tpu.tune.schedulers import (
    ASHAScheduler,
    HyperBandScheduler,
    MedianStoppingRule,
    FIFOScheduler,
    PopulationBasedTraining,
)
from ray_tpu.tune.search import (
    choice,
    grid_search,
    loguniform,
    randint,
    sample_from,
    uniform,
)
from ray_tpu.tune.search import Searcher, TPESearcher
from ray_tpu.tune.trial import Trial
from ray_tpu.tune.tuner import Result, ResultGrid, TuneConfig, Tuner

__all__ = [
    "ASHAScheduler",
    "HyperBandScheduler",
    "MedianStoppingRule",
    "FIFOScheduler",
    "PopulationBasedTraining",
    "Result",
    "ResultGrid",
    "Searcher",
    "TPESearcher",
    "Trial",
    "TuneConfig",
    "Tuner",
    "choice",
    "get_checkpoint",
    "grid_search",
    "loguniform",
    "randint",
    "report",
    "sample_from",
    "uniform",
]
