"""Search spaces + the basic variant generator.

Parity target: reference python/ray/tune/search/sample.py (Domain/Float/
Integer/Categorical, uniform:437, loguniform:480, choice:413, randint:500)
and search/basic_variant.py (BasicVariantGenerator — grid cartesian product
x num_samples random sampling).
"""

from __future__ import annotations

import itertools
import random
from typing import Any, Callable, Optional


class Domain:
    def sample(self, rng: random.Random):
        raise NotImplementedError


class Float(Domain):
    def __init__(self, lower: float, upper: float, log: bool = False):
        if log and lower <= 0:
            raise ValueError("loguniform requires lower > 0")
        self.lower, self.upper, self.log = lower, upper, log

    def sample(self, rng):
        if self.log:
            import math

            return math.exp(rng.uniform(math.log(self.lower), math.log(self.upper)))
        return rng.uniform(self.lower, self.upper)


class Integer(Domain):
    def __init__(self, lower: int, upper: int):
        self.lower, self.upper = lower, upper

    def sample(self, rng):
        return rng.randrange(self.lower, self.upper)


class Categorical(Domain):
    def __init__(self, categories):
        self.categories = list(categories)

    def sample(self, rng):
        return rng.choice(self.categories)


class Function(Domain):
    def __init__(self, fn: Callable):
        self.fn = fn

    def sample(self, rng):
        return self.fn(None)


def uniform(lower: float, upper: float) -> Float:
    return Float(lower, upper)


def loguniform(lower: float, upper: float) -> Float:
    return Float(lower, upper, log=True)


def randint(lower: int, upper: int) -> Integer:
    return Integer(lower, upper)


def choice(categories) -> Categorical:
    return Categorical(categories)


def sample_from(fn: Callable) -> Function:
    return Function(fn)


def grid_search(values: list) -> dict:
    return {"grid_search": list(values)}


def _is_grid(v) -> bool:
    return isinstance(v, dict) and set(v.keys()) == {"grid_search"}


def _walk(space: dict, path=()):
    """Yield (path, value) leaves of a nested param space dict."""
    for k, v in space.items():
        if isinstance(v, dict) and not _is_grid(v):
            yield from _walk(v, path + (k,))
        else:
            yield path + (k,), v


def _set_path(d: dict, path: tuple, value):
    for k in path[:-1]:
        d = d.setdefault(k, {})
    d[path[-1]] = value


class BasicVariantGenerator:
    """Grid cartesian product x num_samples; non-grid Domains resampled per
    variant (reference basic_variant.py)."""

    def __init__(self, seed: Optional[int] = None):
        self._rng = random.Random(seed)

    def generate(self, param_space: dict, num_samples: int) -> list[dict]:
        leaves = list(_walk(param_space or {}))
        grid_axes = [(p, v["grid_search"]) for p, v in leaves if _is_grid(v)]
        combos = list(itertools.product(*[vals for _p, vals in grid_axes])) or [()]
        configs = []
        for _ in range(max(1, num_samples)):
            for combo in combos:
                cfg: dict = {}
                for (p, v) in leaves:
                    if _is_grid(v):
                        continue
                    _set_path(cfg, p, v.sample(self._rng)
                              if isinstance(v, Domain) else v)
                for (p, _vals), val in zip(grid_axes, combo):
                    _set_path(cfg, p, val)
                configs.append(cfg)
        return configs


# ---------------------------------------------------------------- searchers
class Searcher:
    """Model-based suggestion contract (reference tune/search/searcher.py:
    suggest(trial_id) -> config, on_trial_complete(trial_id, result)).
    Used by TuneController when TuneConfig.search_alg is set — trials are
    suggested SEQUENTIALLY as capacity frees, not pre-generated."""

    def set_search_properties(self, metric: str, mode: str,
                              param_space: dict):
        self.metric = metric
        self.mode = mode
        self.param_space = param_space

    def suggest(self, trial_id: str) -> Optional[dict]:
        raise NotImplementedError

    def on_trial_complete(self, trial_id: str, result: Optional[dict]):
        pass

    def observe(self, config: dict, result: Optional[dict]):
        """Feed an externally-evaluated (config, result) pair into the
        model (experiment restore; reference Searcher.add_evaluated_point).
        No-op for model-free searchers."""


class TPESearcher(Searcher):
    """Native Tree-structured Parzen Estimator (Bergstra et al. 2011) —
    the role the reference fills with OptunaSearch (tune/search/optuna/
    optuna_search.py, whose default sampler is also TPE), with no external
    dependency. Observations split into good/bad by the objective's top
    `gamma` quantile; candidates are drawn from the good-points density
    l(x) and ranked by l(x)/g(x). Floats (linear/log) use Parzen windows,
    integers round the continuous result, categoricals use smoothed
    count ratios."""

    def __init__(self, n_startup: int = 8, gamma: float = 0.25,
                 n_candidates: int = 24, seed: Optional[int] = None):
        self.n_startup = n_startup
        self.gamma = gamma
        self.n_candidates = n_candidates
        self.rng = random.Random(seed)
        self._observations: list[tuple[dict, float]] = []  # (config, obj)
        self._live: dict[str, dict] = {}
        self.metric = None
        self.mode = "max"
        self.param_space: dict = {}

    # -- sampling helpers ---------------------------------------------------
    def _random_config(self) -> dict:
        out: dict = {}
        for path, dom in _walk(self.param_space):
            if _is_grid(dom):
                _set_path(out, path, self.rng.choice(dom["grid_search"]))
            elif isinstance(dom, Domain):
                _set_path(out, path, dom.sample(self.rng))
            else:
                _set_path(out, path, dom)
        return out

    @staticmethod
    def _get_path(cfg: dict, path: tuple):
        for p in path:
            cfg = cfg[p]
        return cfg

    def _parzen_best(self, good: list[float], bad: list[float],
                     lower: float, upper: float) -> float:
        """Draw candidates from Parzen windows over `good`, score by
        l/g density ratio, return the best candidate."""
        import math

        span = upper - lower

        def mixture_pdf(x, points, bw):
            # Gaussian mixture + one uniform prior component over the range
            # (keeps g(x) > 0 and leaves room for exploration).
            dens = 1.0 / span
            for p in points:
                dens += math.exp(-0.5 * ((x - p) / bw) ** 2) / (
                    bw * math.sqrt(2 * math.pi))
            return dens / (len(points) + 1)

        # Bandwidth shrinks with the number of good points; the +1 keeps a
        # SINGLE good anchor from getting a whole-range window (candidates
        # then clamp-pile at the domain edges and the model degenerates to
        # edge-probing — observed on log domains).
        bw_good = max(span / (1.0 + len(good)), span * 0.02)
        bw_bad = max(span / (1.0 + math.sqrt(len(bad) or 1)), span * 0.02)
        best_x, best_score = None, -1.0
        for _ in range(self.n_candidates):
            anchor = self.rng.choice(good)
            x = min(upper, max(lower, self.rng.gauss(anchor, bw_good)))
            score = (mixture_pdf(x, good, bw_good)
                     / mixture_pdf(x, bad or [0.5 * (lower + upper)], bw_bad))
            if score > best_score:
                best_x, best_score = x, score
        return best_x

    def _suggest_dim(self, dom, good_vals: list, bad_vals: list):
        import math

        if _is_grid(dom) or isinstance(dom, Categorical):
            cats = dom["grid_search"] if _is_grid(dom) else dom.categories
            # smoothed count ratio; keys by index to tolerate unhashables
            def counts(vals):
                c = [1.0] * len(cats)  # +1 Dirichlet smoothing
                for v in vals:
                    for i, cat in enumerate(cats):
                        if cat == v:
                            c[i] += 1.0
                            break
                total = sum(c)
                return [x / total for x in c]

            lp, gp = counts(good_vals), counts(bad_vals)
            # sample candidates from l, keep the best l/g ratio
            best_i, best_score = 0, -1.0
            for _ in range(self.n_candidates):
                i = self.rng.choices(range(len(cats)), weights=lp)[0]
                score = lp[i] / gp[i]
                if score > best_score:
                    best_i, best_score = i, score
            return cats[best_i]
        if isinstance(dom, Float):
            if dom.log:
                lo, hi = math.log(dom.lower), math.log(dom.upper)
                g = [math.log(v) for v in good_vals]
                b = [math.log(v) for v in bad_vals]
                return math.exp(self._parzen_best(g, b, lo, hi))
            return self._parzen_best(good_vals, bad_vals, dom.lower, dom.upper)
        if isinstance(dom, Integer):
            x = self._parzen_best([float(v) for v in good_vals],
                                  [float(v) for v in bad_vals],
                                  dom.lower, dom.upper)
            return int(min(dom.upper, max(dom.lower, round(x))))
        if isinstance(dom, Function):
            return dom.sample(self.rng)
        return dom  # fixed value

    # -- Searcher contract --------------------------------------------------
    def suggest(self, trial_id: str) -> dict:
        obs = self._observations
        if len(obs) < self.n_startup:
            cfg = self._random_config()
            self._live[trial_id] = cfg
            return cfg
        sign = 1.0 if (self.mode or "max") == "max" else -1.0
        ranked = sorted(obs, key=lambda o: sign * o[1], reverse=True)
        n_good = max(1, int(len(ranked) * self.gamma))
        good, bad = ranked[:n_good], ranked[n_good:]
        cfg: dict = {}
        for path, dom in _walk(self.param_space):
            gv = [self._get_path(c, path) for c, _ in good]
            bv = [self._get_path(c, path) for c, _ in bad]
            _set_path(cfg, path, self._suggest_dim(dom, gv, bv))
        self._live[trial_id] = cfg
        return cfg

    def on_trial_complete(self, trial_id: str, result: Optional[dict]):
        cfg = self._live.pop(trial_id, None)
        if cfg is None:
            return
        self.observe(cfg, result)

    def observe(self, config: dict, result: Optional[dict]):
        if not config or not result or self.metric not in result:
            return
        try:
            obj = float(result[self.metric])
        except (TypeError, ValueError):
            return
        if obj != obj:  # NaN would corrupt the good/bad quantile split
            return
        self._observations.append((config, obj))
