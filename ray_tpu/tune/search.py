"""Search spaces + the basic variant generator.

Parity target: reference python/ray/tune/search/sample.py (Domain/Float/
Integer/Categorical, uniform:437, loguniform:480, choice:413, randint:500)
and search/basic_variant.py (BasicVariantGenerator — grid cartesian product
x num_samples random sampling).
"""

from __future__ import annotations

import itertools
import random
from typing import Any, Callable, Optional


class Domain:
    def sample(self, rng: random.Random):
        raise NotImplementedError


class Float(Domain):
    def __init__(self, lower: float, upper: float, log: bool = False):
        if log and lower <= 0:
            raise ValueError("loguniform requires lower > 0")
        self.lower, self.upper, self.log = lower, upper, log

    def sample(self, rng):
        if self.log:
            import math

            return math.exp(rng.uniform(math.log(self.lower), math.log(self.upper)))
        return rng.uniform(self.lower, self.upper)


class Integer(Domain):
    def __init__(self, lower: int, upper: int):
        self.lower, self.upper = lower, upper

    def sample(self, rng):
        return rng.randrange(self.lower, self.upper)


class Categorical(Domain):
    def __init__(self, categories):
        self.categories = list(categories)

    def sample(self, rng):
        return rng.choice(self.categories)


class Function(Domain):
    def __init__(self, fn: Callable):
        self.fn = fn

    def sample(self, rng):
        return self.fn(None)


def uniform(lower: float, upper: float) -> Float:
    return Float(lower, upper)


def loguniform(lower: float, upper: float) -> Float:
    return Float(lower, upper, log=True)


def randint(lower: int, upper: int) -> Integer:
    return Integer(lower, upper)


def choice(categories) -> Categorical:
    return Categorical(categories)


def sample_from(fn: Callable) -> Function:
    return Function(fn)


def grid_search(values: list) -> dict:
    return {"grid_search": list(values)}


def _is_grid(v) -> bool:
    return isinstance(v, dict) and set(v.keys()) == {"grid_search"}


def _walk(space: dict, path=()):
    """Yield (path, value) leaves of a nested param space dict."""
    for k, v in space.items():
        if isinstance(v, dict) and not _is_grid(v):
            yield from _walk(v, path + (k,))
        else:
            yield path + (k,), v


def _set_path(d: dict, path: tuple, value):
    for k in path[:-1]:
        d = d.setdefault(k, {})
    d[path[-1]] = value


class BasicVariantGenerator:
    """Grid cartesian product x num_samples; non-grid Domains resampled per
    variant (reference basic_variant.py)."""

    def __init__(self, seed: Optional[int] = None):
        self._rng = random.Random(seed)

    def generate(self, param_space: dict, num_samples: int) -> list[dict]:
        leaves = list(_walk(param_space or {}))
        grid_axes = [(p, v["grid_search"]) for p, v in leaves if _is_grid(v)]
        combos = list(itertools.product(*[vals for _p, vals in grid_axes])) or [()]
        configs = []
        for _ in range(max(1, num_samples)):
            for combo in combos:
                cfg: dict = {}
                for (p, v) in leaves:
                    if _is_grid(v):
                        continue
                    _set_path(cfg, p, v.sample(self._rng)
                              if isinstance(v, Domain) else v)
                for (p, _vals), val in zip(grid_axes, combo):
                    _set_path(cfg, p, val)
                configs.append(cfg)
        return configs
