"""Owner-side worker leases: the direct task submission path.

Parity target: the reference NormalTaskSubmitter + lease pools
(core_worker/transport/normal_task_submitter.h:79 — RequestWorkerLease at
normal_task_submitter.cc:296, direct worker-to-worker PushNormalTask at
:186, lease reuse keyed by SchedulingKey). The owner leases workers from the
controller once per scheduling class, then streams task specs DIRECTLY to
the leased workers over coalescing connections; results come back on the
same connection. The controller is out of the per-task hot path entirely —
it only accounts lease resources and brokers worker acquisition.

Failure model (owner-based, like the reference TaskManager): a dead leased
worker fails its in-flight specs back into the class queue (attempt++ up to
max_retries), a `lease_invalid` push from the controller does the same, and
`need_resources` returns idle leases so other demand can place.
"""

from __future__ import annotations

import asyncio
import logging
import threading
import time
from collections import deque
from typing import Optional

from ray_tpu._private import rpc
from ray_tpu._private import tracing as _tracing
from ray_tpu._private.rtconfig import CONFIG
from ray_tpu._private.serialization import dumps_oob
from ray_tpu._private.task_spec import STREAMING, TaskSpec

logger = logging.getLogger(__name__)

# In-flight pipeline depth per leased worker. Tasks beyond the depth wait in
# the class queue; the worker executes its pipeline serially in order.
# 16 (up from 8): at direct-dispatch rates the pump/flush round trip per
# burst is the dominant bubble — measured 9.6k -> 14.1k tasks/s on a
# single saturated lease; still shallow enough that a slow task's
# head-of-line collateral stays bounded. Lease-count ceiling and
# idle-return window live in rtconfig (RT_LEASE_BATCH / RT_LEASE_IDLE_S).
DEPTH = 16
REQUEST_RETRY_S = 0.1
# After the controller answers a scale-up request short, the class stops
# asking for more than it got for this long (a fully-subscribed cluster
# must not be begged at submit rate — the parked requests would fire
# need_resources and steal momentarily-idle leases from their owners).
CAP_PROBE_S = 0.25
# Per-lease assignment depth while the lease set can still GROW: deep
# pipelining must not let the first granted lease swallow a whole small
# batch before its siblings exist (12 slow tasks would all serialize on
# one worker while a second node sits idle). Once the class holds the
# cluster's proven capacity, the full DEPTH applies.
RAMP_DEPTH = 4

_metrics_mod = None


def _record_dispatch(path: str, n: int = 1):
    """Count a task submission route ('direct' vs 'controller') — lazy
    import keeps the module graph acyclic (util.metrics reaches back into
    worker for its flusher)."""
    global _metrics_mod
    if _metrics_mod is None:
        from ray_tpu.util import metrics as _m

        _metrics_mod = _m
    _metrics_mod.record_task_dispatch(path, n)


def _class_key(spec: TaskSpec) -> tuple:
    s = spec.strategy
    return (tuple(sorted(spec.resources.items())), s.kind, s.node_id, s.soft,
            s.pg_id, s.pg_bundle_index)


class _Lease:
    __slots__ = ("lease_id", "worker_id", "node_id", "addr", "conn", "inflight",
                 "buf", "flushing", "dead", "idle_since", "cls", "kill_target",
                 "fail_cause", "incarnation")

    def __init__(self, cls, lease_id: str, worker_id: str, node_id: str,
                 addr: tuple, incarnation: int | None = None):
        self.cls = cls
        self.lease_id = lease_id
        self.worker_id = worker_id
        self.node_id = node_id
        self.addr = addr
        # Node incarnation the grant was minted against: echoed in
        # reasserts so a restarted controller can fence leases from a
        # node's previous life.
        self.incarnation = incarnation
        self.conn: Optional[rpc.Connection] = None
        self.inflight: dict[str, TaskSpec] = {}
        self.buf: list[TaskSpec] = []
        self.flushing = False
        self.dead = False
        self.idle_since = time.monotonic()
        self.fail_cause: Optional[str] = None  # e.g. "oom" from the monitor
        # task_id being force-cancelled via worker kill; while set, the lease
        # takes no new work and _lease_failed requeues innocent bystanders
        # without burning an attempt.
        self.kill_target: Optional[str] = None


class _Class:
    __slots__ = ("key", "resources", "strategy", "queue", "leases", "requesting",
                 "depth", "cap", "cap_ts", "proven_cap")

    def __init__(self, key: tuple, spec: TaskSpec):
        self.key = key
        self.resources = dict(spec.resources)
        self.strategy = spec.strategy
        self.queue: deque[TaskSpec] = deque()
        self.leases: dict[str, _Lease] = {}
        self.requesting = False
        # Grant back-off: a short grant sets cap = what the cluster proved
        # it can give; requests stay under it until the probe window
        # passes (see CAP_PROBE_S).
        self.cap: int | None = None
        self.cap_ts = 0.0
        # Persistent capacity watermark driving the RAMP_DEPTH->DEPTH
        # switch. Unlike `cap` it survives the periodic probes (a probe
        # answered short re-proves it; only a grant that actually GROWS
        # the set clears it), so steady-state pipelining never dips.
        self.proven_cap: int | None = None
        # SPREAD must place per task across nodes (reference spread policy),
        # so no pipelining: each task forces its own lease while the queue
        # is non-empty.
        self.depth = 1 if spec.strategy.kind == "SPREAD" else DEPTH


class LeaseManager:
    """One per Worker process (drivers and executing workers alike)."""

    def __init__(self, worker):
        self.w = worker  # ray_tpu._private.worker.Worker
        self.classes: dict[tuple, _Class] = {}
        self._by_conn: dict = {}  # conn -> _Lease
        self._by_id: dict[str, _Lease] = {}
        self._lock = threading.Lock()
        self._pump_scheduled = False
        self._cancelled: dict[str, bool] = {}  # task_id -> force
        self._idle_task = None
        # worker_id -> (conn, expires): connections of returned leases kept
        # warm — the controller pools returned workers for lease_idle_s, so
        # a regrant usually names a worker we already verified, skipping
        # the TCP connect + whoami round trips of the handoff hot path.
        self._conn_cache: dict[str, tuple] = {}
        self._shutdown = False

    # ------------------------------------------------------------- submit
    def submit(self, spec: TaskSpec):
        """Called from any thread. Refs/resolutions already registered by
        Worker.submit_task."""
        _record_dispatch("direct")
        key = _class_key(spec)
        with self._lock:
            cls = self.classes.get(key)
            if cls is None:
                cls = self.classes[key] = _Class(key, spec)
            cls.queue.append(spec)
            need = not self._pump_scheduled
            self._pump_scheduled = True
        if need:
            self.w.io.spawn(self._a_pump_all())

    # All methods below run on the worker's IO loop.
    async def _a_pump_all(self):
        with self._lock:
            self._pump_scheduled = False
        for cls in list(self.classes.values()):
            self._pump(cls)
        if self._idle_task is None and not self._shutdown:
            self._idle_task = asyncio.ensure_future(self._a_idle_loop())

    def _pump(self, cls: _Class):
        # Assign queued specs to the least-loaded live leases (skip leases
        # whose worker is being force-kill-cancelled: it is already doomed).
        # Specs are handed out in per-lease batches (ONE lock acquisition +
        # ONE flush kick per round): a burst of N submissions costs
        # O(leases) lock/min() rounds, not O(N). Each round takes at most
        # ceil(queue/live) specs so a burst smaller than depth*leases still
        # SPREADS across the live leases instead of convoying on one.
        live = [l for l in cls.leases.values()
                if not l.dead and l.kill_target is None]
        if cls.depth == 1:  # SPREAD: per-task placement, no pipelining
            eff_depth = 1
        elif cls.proven_cap is not None and len(live) >= cls.proven_cap:
            eff_depth = cls.depth
        else:
            # Lease set may still grow: stay shallow so a small batch
            # leaves queue for the leases about to be granted.
            eff_depth = RAMP_DEPTH
        while cls.queue and live:
            lease = min(live, key=lambda l: len(l.inflight))
            room = eff_depth - len(lease.inflight)
            if room <= 0:
                break
            batch = []
            with self._lock:
                qlen = len(cls.queue)
                take = min(room, -(-qlen // len(live)))
                for _ in range(min(take, qlen)):
                    batch.append(cls.queue.popleft())
            if not batch:
                break
            assigned = False
            for spec in batch:
                if self._consume_cancel_queued(spec):
                    continue
                lease.inflight[spec.task_id] = spec
                lease.buf.append(spec)
                assigned = True
            if assigned and not lease.flushing:
                lease.flushing = True
                asyncio.ensure_future(self._a_flush(lease))
        if cls.queue and not cls.requesting:
            outstanding = len(cls.queue) + sum(len(l.inflight) for l in live)
            want = min(max(1, CONFIG.lease_batch), outstanding)
            if cls.cap is not None:
                if time.monotonic() - cls.cap_ts >= CAP_PROBE_S:
                    cls.cap = None  # probe again: capacity may have freed
                else:
                    want = min(want, cls.cap)
            need = want - len(cls.leases)
            # Slow-start (ask at most double the current holding): under
            # multi-client contention the first requester must not vacuum
            # the whole pool and leave its peers starving — redistribution
            # afterwards costs rounds of need_resources churn. A lone
            # client still reaches lease_batch in a handful of cheap
            # doubling grants.
            need = min(need, max(1, len(cls.leases)))
            if need > 0:
                cls.requesting = True
                asyncio.ensure_future(self._a_request(cls, need))

    def _consume_cancel_queued(self, spec: TaskSpec) -> bool:
        force = self._cancelled.pop(spec.task_id, None)
        if force is None:
            return False
        self._fail_spec(spec, {"type": "TaskCancelledError",
                               "message": f"task {spec.name} cancelled"})
        return True

    async def _a_request(self, cls: _Class, count: int):
        have = sum(1 for l in cls.leases.values() if not l.dead)
        try:
            rep = await self.w.controller.call(
                "lease_workers", resources=cls.resources, strategy=cls.strategy,
                count=count, have=have, owner_id=self.w.worker_id)
        except Exception:
            rep = {"leases": []}
        finally:
            cls.requesting = False
        if len(rep["leases"]) < count:
            # The cluster gave less than asked: remember the proven level
            # and stop begging until the probe window passes.
            cls.cap = max(1, len(cls.leases) + len(rep["leases"]))
            cls.cap_ts = time.monotonic()
            cls.proven_cap = cls.cap
        else:
            cls.cap = None
            if rep["leases"]:
                # The set actually grew to (or past) what was asked:
                # capacity is unknown again — ramp shallow until the next
                # short answer re-proves the ceiling.
                cls.proven_cap = None
        for g in rep["leases"]:
            lease = _Lease(cls, g["lease_id"], g["worker_id"], g["node_id"],
                           tuple(g["address"]), g.get("incarnation"))
            cls.leases[lease.lease_id] = lease
            self._by_id[lease.lease_id] = lease
            asyncio.ensure_future(self._a_connect(lease))
        if not rep["leases"] and cls.queue and not any(
                not l.dead for l in cls.leases.values()):
            # Nothing placeable right now: poll until resources free up
            # (node death recovery, infeasible-demand waiting).
            await asyncio.sleep(REQUEST_RETRY_S)
            if not self._shutdown:
                self._pump(cls)

    async def _a_connect(self, lease: _Lease):
        cached = self._conn_cache.pop(lease.worker_id, None)
        if cached is not None and not cached[0].closed:
            # Warm-pool regrant of a worker we already talked to: the
            # connection's identity was verified when first established and
            # a connection to a dead worker closes, so reuse it as-is — no
            # TCP connect, no whoami round trip.
            conn = cached[0]
        else:
            try:
                conn = await rpc.connect(
                    *lease.addr, on_push=self._on_worker_push,
                    on_close=self._on_worker_conn_close, timeout=10,
                    label="lease")
                rep = await conn.call("whoami", _timeout=10)
                if rep.get("worker_id") != lease.worker_id:
                    await conn.close()
                    raise ConnectionError("stale lease address (port reused)")
            except Exception as e:
                logger.warning("lease %s connect failed: %s",
                               lease.lease_id[:8], e)
                self._lease_failed(lease)
                return
        lease.conn = conn
        self._by_conn[conn] = lease
        if lease.dead:  # invalidated while connecting
            self._park_conn(lease)
            return
        self._pump(lease.cls)
        if lease.buf and not lease.flushing:
            lease.flushing = True
            asyncio.ensure_future(self._a_flush(lease))

    def _park_conn(self, lease: _Lease):
        """Detach and cache a (healthy) lease connection for reuse by a
        later grant of the same worker; close it when the cache is full."""
        conn = lease.conn
        lease.conn = None
        if conn is None:
            return
        self._by_conn.pop(conn, None)
        if conn.closed:
            return
        if len(self._conn_cache) >= 32:
            asyncio.ensure_future(conn.close())
            return
        self._conn_cache[lease.worker_id] = (
            conn, time.monotonic() + CONFIG.lease_idle_s + 2.0)

    async def _a_flush(self, lease: _Lease):
        while True:
            if lease.conn is None:
                lease.flushing = False
                return  # _a_connect flushes once connected
            batch = lease.buf
            lease.buf = []
            if not batch:
                lease.flushing = False
                return
            try:
                # Compact wire form (see TaskSpec.task_call_tuple): the
                # frame-constant owner + class resources ride once; per-spec
                # fields go as tuples instead of full 24-field spec pickles.
                await lease.conn.push(
                    "exec_tasks",
                    common=(self.w.worker_id, self.w.server_addr,
                            lease.cls.resources),
                    calls=[s.task_call_tuple() for s in batch])
                for s in batch:
                    if s.trace is not None:
                        _tracing.record_instant(
                            s.trace, "dispatch", "dispatch",
                            {"task": s.task_id,
                             "worker": lease.worker_id[:12]})
            except Exception:
                lease.flushing = False
                self._lease_failed(lease)
                return

    # ----------------------------------------------------------- results
    async def _on_worker_push(self, conn, method, a):
        if method == "gen_items":
            # Needs no lease binding: trailing stream items may arrive on a
            # connection that was parked in the cache after its lease
            # retired (the old path closed the conn and lost them anyway).
            self.w._on_gen_items(conn, a["items"])
            return
        lease = self._by_conn.get(conn)
        if lease is None:
            return
        if method == "tasks_done":
            for item in a["done"]:
                self._task_done(lease, item)
            lease.idle_since = time.monotonic()
            self._pump(lease.cls)

    def _task_done(self, lease: _Lease, item: tuple):
        # item: (task_id, attempt, results, error, retryable, exec_failure)
        tid, _attempt, results, error, retryable, _ef = item  # rtcheck: wire=tasks_done.item
        spec = lease.inflight.pop(tid, None)
        if spec is None:
            self._cancelled.pop(tid, None)
            return
        self._cancelled.pop(tid, None)
        if (error is not None and retryable
                and spec.attempt < spec.max_retries):
            spec.attempt += 1
            with self._lock:
                lease.cls.queue.appendleft(spec)
            return
        if spec.trace is not None:
            _tracing.record_instant(spec.trace, "result", "result",
                                    {"task": tid, "ok": error is None})
        for oid, inline, size, holder in results or ():
            res = self.w._resolutions.get(oid)
            if res is not None:
                res.resolve(inline, [tuple(holder)] if holder else [], error)
        if lease.cls.strategy.kind == "SPREAD" and not lease.inflight:
            # SPREAD is a PER-TASK placement decision (reference spread
            # policy): return the lease after its task so the controller
            # places the next one fresh — reusing it would funnel a burst
            # through whichever node connected first.
            self._retire_lease(lease)

    def _retire_lease(self, lease: _Lease):
        if lease.dead:
            return
        lease.dead = True
        lease.cls.leases.pop(lease.lease_id, None)
        self._by_id.pop(lease.lease_id, None)
        self._park_conn(lease)
        asyncio.ensure_future(self._a_return([lease.lease_id]))

    def _fail_spec(self, spec: TaskSpec, blob: dict):
        h, bufs = dumps_oob(blob)
        err = [h, *bufs]
        for oid in spec.return_object_ids():
            res = self.w._resolutions.get(oid)
            if res is not None:
                res.resolve(None, [], err)

    # ----------------------------------------------------------- failure
    def _on_worker_conn_close(self, conn):
        lease = self._by_conn.pop(conn, None)
        for wid, (c, _exp) in list(self._conn_cache.items()):
            if c is conn:
                self._conn_cache.pop(wid, None)
        if not self._shutdown:
            self.w._gen_conn_lost(conn)
        if lease is not None and not self._shutdown:
            self._lease_failed(lease)

    def _lease_failed(self, lease: _Lease):
        """Worker/connection died; drop the lease and re-route its specs.

        Transport sever (no known cause — the worker may well be alive and
        still executing its pipeline): SENT specs fail over to the classic
        CONTROLLER path without burning an attempt. At-most-once holds
        because the worker skips the unstarted specs of a dead holder
        connection and reports the one that WAS executing to its node
        agent, whose task-id dedup parks/absorbs the failover re-dispatch.
        (A worker that really died mid-task leaves no record, so the
        failover re-executes it — the same at-least-once window every
        retry has.)

        Known worker death (lease_invalid / OOM / force-kill) keeps the
        original owner-side retry semantics.

        The lease id is ALWAYS returned to the controller: for a
        severed-but-alive worker that's what frees (and warm-pools) the
        slot — the old keep-the-lease behavior leaked it until the owner
        process exited; for a dead worker the return races the agent's
        worker_died report and loses harmlessly."""
        if lease.dead:
            return
        lease.dead = True
        lease.cls.leases.pop(lease.lease_id, None)
        self._by_id.pop(lease.lease_id, None)
        if lease.conn is not None:
            self._by_conn.pop(lease.conn, None)
        requeue = []
        failover = []
        # Specs still in lease.buf provably never reached the worker; of the
        # rest, worker exec order == arrival order and _task_done pops
        # completions, so the OLDEST remaining SENT spec is the one that may
        # have been executing when the worker died; everything younger never
        # started.
        unsent = {s.task_id for s in lease.buf}
        executing_candidate = next(
            (tid for tid in lease.inflight if tid not in unsent), None)
        sever = (lease.fail_cause is None and lease.kill_target is None
                 and CONFIG.direct_dispatch)
        for spec in lease.inflight.values():
            force = self._cancelled.pop(spec.task_id, None)
            if force is not None:
                self._fail_spec(spec, {
                    "type": "WorkerCrashedError" if force else "TaskCancelledError",
                    "message": f"task {spec.name} cancelled"})
            elif spec.task_id in unsent:
                # Never sent: requeue without burning an attempt, whatever
                # killed the worker.
                requeue.append(spec)
            elif sever and spec.num_returns != STREAMING:
                # Sent to a worker we can no longer talk to: controller
                # failover (streaming specs stay on the lease path — the
                # controller transport has no item stream).
                failover.append(spec)
            elif (lease.kill_target is not None
                  and spec.task_id != executing_candidate):
                # The worker was killed to force-cancel ONE task; this spec is
                # an unstarted bystander pipelined behind it (a reference
                # leased worker runs one task at a time, so it has no such
                # collateral). Requeue WITHOUT burning a retry attempt. The
                # executing candidate deliberately falls through to normal
                # retry semantics: re-running a possibly-started task for
                # free could duplicate side effects of a max_retries=0 task.
                requeue.append(spec)
            elif spec.attempt < spec.max_retries:
                spec.attempt += 1
                requeue.append(spec)
            elif lease.fail_cause == "oom":
                self._fail_spec(spec, {
                    "type": "OutOfMemoryError",
                    "message": f"leased worker {lease.worker_id[:8]} was "
                               f"killed by the node memory monitor"})
            elif lease.fail_cause == "stall":
                self._fail_spec(spec, {
                    "type": "WorkerCrashedError",
                    "message": f"leased worker {lease.worker_id[:8]} was "
                               f"killed by the stall watchdog (no progress "
                               f"past RT_STALL_KILL_S; see "
                               f"util.state.list_stalls())"})
            else:
                self._fail_spec(spec, {
                    "type": "WorkerCrashedError",
                    "message": f"leased worker {lease.worker_id[:8]} died"})
        lease.inflight.clear()
        if requeue:
            with self._lock:
                for spec in reversed(requeue):
                    lease.cls.queue.appendleft(spec)
        asyncio.ensure_future(self._a_return([lease.lease_id]))
        if failover:
            logger.warning(
                "lease %s severed: failing %d in-flight spec(s) over to the "
                "controller path", lease.lease_id[:8], len(failover))
            # Owner-side event: when the direct connection drops BEFORE the
            # controller hears of the worker's death, the owner is the only
            # process that knows a failover happened (the controller may
            # see only a routine lease return).
            from ray_tpu._private import events as _events

            _events.emit_event(
                "lease_failover",
                f"lease {lease.lease_id[:8]} severed: {len(failover)} "
                f"in-flight spec(s) fail over to the controller path",
                entity=(lease.lease_id, lease.worker_id),
                attrs={"path": "owner_sever", "specs": len(failover)})
            self.w.submit_specs_via_controller(failover)
        if lease.cls.queue:
            self._pump(lease.cls)

    def task_status(self, task_id: str) -> dict | None:
        """Best-effort status of a task this owner submitted on the direct
        path (GetTimeoutError enrichment). Read-only scan from the caller's
        thread; deliberately racy — diagnostics must not take loop-side
        locks or block on the IO thread."""
        try:
            with self._lock:
                for cls in self.classes.values():
                    for spec in cls.queue:
                        if spec.task_id == task_id:
                            return {"found": True, "state": "queued",
                                    "via": "direct", "name": spec.name,
                                    "attempt": spec.attempt,
                                    "node_id": None, "worker_id": None,
                                    "beacon_age_s": None}
            for lease in list(self._by_id.values()):
                spec = lease.inflight.get(task_id)
                if spec is None:
                    continue
                sent = all(s.task_id != task_id for s in list(lease.buf))
                return {"found": True,
                        "state": "running" if sent else "queued",
                        "via": "direct", "name": spec.name,
                        "attempt": spec.attempt, "node_id": lease.node_id,
                        "worker_id": lease.worker_id, "beacon_age_s": None}
        except Exception:
            pass
        return None

    def on_lease_invalid(self, lease_id: str, cause: str | None = None):
        lease = self._by_id.get(lease_id)
        if lease is not None:
            # A controller invalidation IS a known worker death (the agent
            # reported it): keep retry semantics, don't treat as a sever.
            lease.fail_cause = cause or "worker died"
            self._lease_failed(lease)

    # -------------------------------------------------------- cancellation
    def cancel(self, task_id: str, force: bool) -> bool:
        """True if the task is managed here (queued or in flight).

        Called from the user's thread, but every structure it touches beyond
        the lock-guarded class queues (lease.inflight, lease.buf) is owned by
        loop-side code (_pump/_task_done/_a_flush), so the scan+mutation runs
        as one atomic step ON the IO loop."""

        async def _go() -> bool:
            with self._lock:
                for cls in self.classes.values():
                    for spec in cls.queue:
                        if spec.task_id == task_id:
                            cls.queue.remove(spec)
                            self._fail_spec(spec, {
                                "type": "TaskCancelledError",
                                "message": f"task {spec.name} cancelled"})
                            return True
            for lease in list(self._by_id.values()):
                spec = lease.inflight.get(task_id)
                if spec is None:
                    continue
                self._cancelled[task_id] = force
                spec.max_retries = 0  # never retry a cancelled task
                if spec in lease.buf:
                    # Never sent to the worker: unbuffer and fail immediately
                    # (reference cancels pre-dispatch tasks synchronously).
                    # Applies to force too — killing the worker for a spec it
                    # never received would only hurt innocent neighbors.
                    lease.buf.remove(spec)
                    lease.inflight.pop(task_id, None)
                    self._cancelled.pop(task_id, None)
                    self._fail_spec(spec, {"type": "TaskCancelledError",
                                           "message": f"task {spec.name} cancelled"})
                elif force:
                    # Kill the worker, but do NOT requeue pipelined neighbors
                    # yet: they are requeued (attempt intact) by _lease_failed
                    # once the death is actually observed, so a neighbor can
                    # never run twice concurrently. Setting kill_target takes
                    # the lease out of _pump rotation immediately.
                    lease.kill_target = task_id
                    asyncio.ensure_future(
                        self._a_kill_for_cancel(lease, task_id))
                else:
                    # Already on the worker (queued or executing there).
                    # Don't guess the outcome: push the cancel and let the
                    # worker's tasks_done report decide — a value if the task
                    # wins the race (reference: ray.cancel losing the race
                    # delivers the value), a TaskCancelledError if the
                    # interrupt/skip wins.
                    if lease.conn is not None:
                        asyncio.ensure_future(
                            lease.conn.push("cancel", task_id=task_id))
                return True
            return False

        return self.w.io.run(_go())

    async def _a_kill_for_cancel(self, lease: _Lease, task_id: str):
        """Deliver a force-cancel kill, then make sure the doomed state
        resolves: a lease must never stay out of _pump rotation forever.

        - kill delivered → wait (bounded) for the death to arrive as a conn
          close; if it never does (kill push lost downstream), declare the
          lease failed ourselves so the class unblocks.
        - kill undeliverable (lease already torn down, controller blip) →
          un-doom: force cancel is best-effort in the reference too — the
          task then simply runs to completion and tasks_done decides the
          ref's outcome."""
        delivered = False
        for attempt in range(2):
            try:
                rep = await self.w.controller.call(
                    "kill_leased_worker", worker_id=lease.worker_id)
            except Exception:
                await asyncio.sleep(0.2)
                continue
            delivered = bool(rep.get("killed"))
            break
        # Grace period even when undeliverable: a concurrent kill (second
        # force-cancel on the same lease) may already be felling the worker.
        deadline = time.monotonic() + (10.0 if delivered else 1.0)
        while not lease.dead and time.monotonic() < deadline:
            await asyncio.sleep(0.05)
        if lease.dead:
            return
        if delivered:
            self._lease_failed(lease)
        elif lease.kill_target == task_id:
            lease.kill_target = None
            self._pump(lease.cls)

    # ------------------------------------------------------ lease returns
    async def _a_idle_loop(self):
        while not self._shutdown:
            await asyncio.sleep(min(0.25, max(0.05, CONFIG.lease_idle_s / 2)))
            now = time.monotonic()
            to_return = []
            for cls in self.classes.values():
                if cls.queue:
                    continue
                for lease in list(cls.leases.values()):
                    if (not lease.dead and not lease.inflight and not lease.buf
                            and now - lease.idle_since > CONFIG.lease_idle_s):
                        lease.dead = True
                        cls.leases.pop(lease.lease_id, None)
                        self._by_id.pop(lease.lease_id, None)
                        to_return.append(lease)
            if to_return:
                for lease in to_return:
                    self._park_conn(lease)
                await self._a_return([l.lease_id for l in to_return])
            # Cache sweep: drop dead or expired parked connections.
            for wid, (c, exp) in list(self._conn_cache.items()):
                if c.closed or exp < now:
                    self._conn_cache.pop(wid, None)
                    if not c.closed:
                        asyncio.ensure_future(c.close())

    def reassert(self):
        """After a controller restart: re-declare every live lease so the
        new controller can rebuild its lease table + resource accounting
        (reference: raylets report held leases when the GCS restarts).
        Runs on the IO loop (called from the reconnect coroutine)."""
        entries = []
        for lease in self._by_id.values():
            if lease.dead:
                continue
            entries.append({
                "lease_id": lease.lease_id,
                "worker_id": lease.worker_id,
                "node_id": lease.node_id,
                "address": lease.addr,
                "incarnation": lease.incarnation,
                "resources": lease.cls.resources,
                "strategy": lease.cls.strategy,
            })
        if entries:
            asyncio.ensure_future(self.w.controller.push(
                "reassert_leases", leases=entries,
                owner_id=self.w.worker_id))

    def on_need_resources(self):
        """Controller has demand it can't place: return idle leases now."""
        self.w.io.spawn(self._a_return_idle())

    async def _a_return_idle(self):
        to_return = []
        for cls in self.classes.values():
            if cls.queue:
                continue
            for lease in list(cls.leases.values()):
                if not lease.dead and not lease.inflight and not lease.buf:
                    lease.dead = True
                    cls.leases.pop(lease.lease_id, None)
                    self._by_id.pop(lease.lease_id, None)
                    self._park_conn(lease)
                    to_return.append(lease.lease_id)
        if to_return:
            await self._a_return(to_return)

    async def _a_return(self, lease_ids: list[str]):
        try:
            await self.w.controller.call("return_leases", lease_ids=lease_ids)
        except Exception:
            pass

    def shutdown(self):
        self._shutdown = True
        ids = list(self._by_id)
        if ids:
            try:
                self.w.io.run(self._a_return(ids), timeout=2)
            except Exception:
                pass
        cached, self._conn_cache = list(self._conn_cache.values()), {}
        for c, _exp in cached:
            if not c.closed:
                try:
                    self.w.io.spawn(c.close())
                except Exception:
                    pass
