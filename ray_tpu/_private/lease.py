"""Owner-side worker leases: the direct task submission path.

Parity target: the reference NormalTaskSubmitter + lease pools
(core_worker/transport/normal_task_submitter.h:79 — RequestWorkerLease at
normal_task_submitter.cc:296, direct worker-to-worker PushNormalTask at
:186, lease reuse keyed by SchedulingKey). The owner leases workers from the
controller once per scheduling class, then streams task specs DIRECTLY to
the leased workers over coalescing connections; results come back on the
same connection. The controller is out of the per-task hot path entirely —
it only accounts lease resources and brokers worker acquisition.

Failure model (owner-based, like the reference TaskManager): a dead leased
worker fails its in-flight specs back into the class queue (attempt++ up to
max_retries), a `lease_invalid` push from the controller does the same, and
`need_resources` returns idle leases so other demand can place.
"""

from __future__ import annotations

import asyncio
import logging
import threading
import time
from collections import deque
from typing import Optional

from ray_tpu._private import rpc
from ray_tpu._private.serialization import dumps_oob
from ray_tpu._private.task_spec import TaskSpec

logger = logging.getLogger(__name__)

# In-flight pipeline depth per leased worker. Tasks beyond the depth wait in
# the class queue; the worker executes its pipeline serially in order.
DEPTH = 8
MAX_LEASES_PER_CLASS = 16
IDLE_RETURN_S = 0.5
REQUEST_RETRY_S = 0.1


def _class_key(spec: TaskSpec) -> tuple:
    s = spec.strategy
    return (tuple(sorted(spec.resources.items())), s.kind, s.node_id, s.soft,
            s.pg_id, s.pg_bundle_index)


class _Lease:
    __slots__ = ("lease_id", "worker_id", "node_id", "addr", "conn", "inflight",
                 "buf", "flushing", "dead", "idle_since", "cls", "kill_target",
                 "fail_cause")

    def __init__(self, cls, lease_id: str, worker_id: str, node_id: str, addr: tuple):
        self.cls = cls
        self.lease_id = lease_id
        self.worker_id = worker_id
        self.node_id = node_id
        self.addr = addr
        self.conn: Optional[rpc.Connection] = None
        self.inflight: dict[str, TaskSpec] = {}
        self.buf: list[TaskSpec] = []
        self.flushing = False
        self.dead = False
        self.idle_since = time.monotonic()
        self.fail_cause: Optional[str] = None  # e.g. "oom" from the monitor
        # task_id being force-cancelled via worker kill; while set, the lease
        # takes no new work and _lease_failed requeues innocent bystanders
        # without burning an attempt.
        self.kill_target: Optional[str] = None


class _Class:
    __slots__ = ("key", "resources", "strategy", "queue", "leases", "requesting",
                 "depth")

    def __init__(self, key: tuple, spec: TaskSpec):
        self.key = key
        self.resources = dict(spec.resources)
        self.strategy = spec.strategy
        self.queue: deque[TaskSpec] = deque()
        self.leases: dict[str, _Lease] = {}
        self.requesting = False
        # SPREAD must place per task across nodes (reference spread policy),
        # so no pipelining: each task forces its own lease while the queue
        # is non-empty.
        self.depth = 1 if spec.strategy.kind == "SPREAD" else DEPTH


class LeaseManager:
    """One per Worker process (drivers and executing workers alike)."""

    def __init__(self, worker):
        self.w = worker  # ray_tpu._private.worker.Worker
        self.classes: dict[tuple, _Class] = {}
        self._by_conn: dict = {}  # conn -> _Lease
        self._by_id: dict[str, _Lease] = {}
        self._lock = threading.Lock()
        self._pump_scheduled = False
        self._cancelled: dict[str, bool] = {}  # task_id -> force
        self._idle_task = None
        self._shutdown = False

    # ------------------------------------------------------------- submit
    def submit(self, spec: TaskSpec):
        """Called from any thread. Refs/resolutions already registered by
        Worker.submit_task."""
        key = _class_key(spec)
        with self._lock:
            cls = self.classes.get(key)
            if cls is None:
                cls = self.classes[key] = _Class(key, spec)
            cls.queue.append(spec)
            need = not self._pump_scheduled
            self._pump_scheduled = True
        if need:
            self.w.io.spawn(self._a_pump_all())

    # All methods below run on the worker's IO loop.
    async def _a_pump_all(self):
        with self._lock:
            self._pump_scheduled = False
        for cls in list(self.classes.values()):
            self._pump(cls)
        if self._idle_task is None and not self._shutdown:
            self._idle_task = asyncio.ensure_future(self._a_idle_loop())

    def _pump(self, cls: _Class):
        # Assign queued specs to the least-loaded live leases (skip leases
        # whose worker is being force-kill-cancelled: it is already doomed).
        # Specs are handed out in per-lease batches (ONE lock acquisition +
        # ONE flush kick per round): a burst of N submissions costs
        # O(leases) lock/min() rounds, not O(N). Each round takes at most
        # ceil(queue/live) specs so a burst smaller than depth*leases still
        # SPREADS across the live leases instead of convoying on one.
        live = [l for l in cls.leases.values()
                if not l.dead and l.kill_target is None]
        while cls.queue and live:
            lease = min(live, key=lambda l: len(l.inflight))
            room = cls.depth - len(lease.inflight)
            if room <= 0:
                break
            batch = []
            with self._lock:
                qlen = len(cls.queue)
                take = min(room, -(-qlen // len(live)))
                for _ in range(min(take, qlen)):
                    batch.append(cls.queue.popleft())
            if not batch:
                break
            assigned = False
            for spec in batch:
                if self._consume_cancel_queued(spec):
                    continue
                lease.inflight[spec.task_id] = spec
                lease.buf.append(spec)
                assigned = True
            if assigned and not lease.flushing:
                lease.flushing = True
                asyncio.ensure_future(self._a_flush(lease))
        if cls.queue and not cls.requesting:
            outstanding = len(cls.queue) + sum(len(l.inflight) for l in live)
            want = min(MAX_LEASES_PER_CLASS, outstanding)
            need = want - len(cls.leases)
            if need > 0:
                cls.requesting = True
                asyncio.ensure_future(self._a_request(cls, need))

    def _consume_cancel_queued(self, spec: TaskSpec) -> bool:
        force = self._cancelled.pop(spec.task_id, None)
        if force is None:
            return False
        self._fail_spec(spec, {"type": "TaskCancelledError",
                               "message": f"task {spec.name} cancelled"})
        return True

    async def _a_request(self, cls: _Class, count: int):
        try:
            rep = await self.w.controller.call(
                "lease_workers", resources=cls.resources, strategy=cls.strategy,
                count=count, owner_id=self.w.worker_id)
        except Exception:
            rep = {"leases": []}
        finally:
            cls.requesting = False
        for g in rep["leases"]:
            lease = _Lease(cls, g["lease_id"], g["worker_id"], g["node_id"],
                           tuple(g["address"]))
            cls.leases[lease.lease_id] = lease
            self._by_id[lease.lease_id] = lease
            asyncio.ensure_future(self._a_connect(lease))
        if not rep["leases"] and cls.queue and not any(
                not l.dead for l in cls.leases.values()):
            # Nothing placeable right now: poll until resources free up
            # (node death recovery, infeasible-demand waiting).
            await asyncio.sleep(REQUEST_RETRY_S)
            if not self._shutdown:
                self._pump(cls)

    async def _a_connect(self, lease: _Lease):
        try:
            conn = await rpc.connect(
                *lease.addr, on_push=self._on_worker_push,
                on_close=self._on_worker_conn_close, timeout=10,
                label="lease")
            rep = await conn.call("whoami", _timeout=10)
            if rep.get("worker_id") != lease.worker_id:
                await conn.close()
                raise ConnectionError("stale lease address (port reused)")
        except Exception as e:
            logger.warning("lease %s connect failed: %s", lease.lease_id[:8], e)
            self._lease_failed(lease, release=True)
            return
        lease.conn = conn
        self._by_conn[conn] = lease
        if lease.dead:  # invalidated while connecting
            await conn.close()
            return
        self._pump(lease.cls)
        if lease.buf and not lease.flushing:
            lease.flushing = True
            asyncio.ensure_future(self._a_flush(lease))

    async def _a_flush(self, lease: _Lease):
        while True:
            if lease.conn is None:
                lease.flushing = False
                return  # _a_connect flushes once connected
            batch = lease.buf
            lease.buf = []
            if not batch:
                lease.flushing = False
                return
            try:
                await lease.conn.push("exec_tasks", specs=batch)
            except Exception:
                lease.flushing = False
                self._lease_failed(lease, release=False)
                return

    # ----------------------------------------------------------- results
    async def _on_worker_push(self, conn, method, a):
        lease = self._by_conn.get(conn)
        if lease is None:
            return
        if method == "tasks_done":
            for item in a["done"]:
                self._task_done(lease, item)
            lease.idle_since = time.monotonic()
            self._pump(lease.cls)
        elif method == "gen_items":
            self.w._on_gen_items(conn, a["items"])

    def _task_done(self, lease: _Lease, item: dict):
        spec = lease.inflight.pop(item["task_id"], None)
        if spec is None:
            self._cancelled.pop(item["task_id"], None)
            return
        self._cancelled.pop(spec.task_id, None)
        error = item.get("error")
        if (error is not None and item.get("retryable")
                and spec.attempt < spec.max_retries):
            spec.attempt += 1
            with self._lock:
                lease.cls.queue.appendleft(spec)
            return
        for oid, inline, size, holder in item.get("results", []):
            res = self.w._resolutions.get(oid)
            if res is not None:
                res.resolve(inline, [tuple(holder)] if holder else [], error)
        if lease.cls.strategy.kind == "SPREAD" and not lease.inflight:
            # SPREAD is a PER-TASK placement decision (reference spread
            # policy): return the lease after its task so the controller
            # places the next one fresh — reusing it would funnel a burst
            # through whichever node connected first.
            self._retire_lease(lease)

    def _retire_lease(self, lease: _Lease):
        if lease.dead:
            return
        lease.dead = True
        lease.cls.leases.pop(lease.lease_id, None)
        self._by_id.pop(lease.lease_id, None)
        if lease.conn is not None:
            self._by_conn.pop(lease.conn, None)
            asyncio.ensure_future(lease.conn.close())
        asyncio.ensure_future(self._a_return([lease.lease_id]))

    def _fail_spec(self, spec: TaskSpec, blob: dict):
        h, bufs = dumps_oob(blob)
        err = [h, *bufs]
        for oid in spec.return_object_ids():
            res = self.w._resolutions.get(oid)
            if res is not None:
                res.resolve(None, [], err)

    # ----------------------------------------------------------- failure
    def _on_worker_conn_close(self, conn):
        lease = self._by_conn.pop(conn, None)
        if not self._shutdown:
            self.w._gen_conn_lost(conn)
        if lease is not None and not self._shutdown:
            self._lease_failed(lease, release=False)

    def _lease_failed(self, lease: _Lease, release: bool):
        """Worker/connection died. Retry its in-flight specs (attempt++) or
        fail them; drop the lease. The controller learns of worker death from
        the node agent and releases resources; `release` covers the
        connect-failed case where no such signal will come."""
        if lease.dead:
            return
        lease.dead = True
        lease.cls.leases.pop(lease.lease_id, None)
        self._by_id.pop(lease.lease_id, None)
        if lease.conn is not None:
            self._by_conn.pop(lease.conn, None)
        requeue = []
        # Specs still in lease.buf provably never reached the worker; of the
        # rest, worker exec order == arrival order and _task_done pops
        # completions, so the OLDEST remaining SENT spec is the one that may
        # have been executing when the worker died; everything younger never
        # started.
        unsent = {s.task_id for s in lease.buf}
        executing_candidate = next(
            (tid for tid in lease.inflight if tid not in unsent), None)
        for spec in lease.inflight.values():
            force = self._cancelled.pop(spec.task_id, None)
            if force is not None:
                self._fail_spec(spec, {
                    "type": "WorkerCrashedError" if force else "TaskCancelledError",
                    "message": f"task {spec.name} cancelled"})
            elif spec.task_id in unsent:
                # Never sent: requeue without burning an attempt, whatever
                # killed the worker.
                requeue.append(spec)
            elif (lease.kill_target is not None
                  and spec.task_id != executing_candidate):
                # The worker was killed to force-cancel ONE task; this spec is
                # an unstarted bystander pipelined behind it (a reference
                # leased worker runs one task at a time, so it has no such
                # collateral). Requeue WITHOUT burning a retry attempt. The
                # executing candidate deliberately falls through to normal
                # retry semantics: re-running a possibly-started task for
                # free could duplicate side effects of a max_retries=0 task.
                requeue.append(spec)
            elif spec.attempt < spec.max_retries:
                spec.attempt += 1
                requeue.append(spec)
            elif lease.fail_cause == "oom":
                self._fail_spec(spec, {
                    "type": "OutOfMemoryError",
                    "message": f"leased worker {lease.worker_id[:8]} was "
                               f"killed by the node memory monitor"})
            else:
                self._fail_spec(spec, {
                    "type": "WorkerCrashedError",
                    "message": f"leased worker {lease.worker_id[:8]} died"})
        lease.inflight.clear()
        if requeue:
            with self._lock:
                for spec in reversed(requeue):
                    lease.cls.queue.appendleft(spec)
        if release:
            asyncio.ensure_future(self._a_return([lease.lease_id]))
        if lease.cls.queue:
            self._pump(lease.cls)

    def on_lease_invalid(self, lease_id: str, cause: str | None = None):
        lease = self._by_id.get(lease_id)
        if lease is not None:
            lease.fail_cause = cause
            self._lease_failed(lease, release=False)

    # -------------------------------------------------------- cancellation
    def cancel(self, task_id: str, force: bool) -> bool:
        """True if the task is managed here (queued or in flight).

        Called from the user's thread, but every structure it touches beyond
        the lock-guarded class queues (lease.inflight, lease.buf) is owned by
        loop-side code (_pump/_task_done/_a_flush), so the scan+mutation runs
        as one atomic step ON the IO loop."""

        async def _go() -> bool:
            with self._lock:
                for cls in self.classes.values():
                    for spec in cls.queue:
                        if spec.task_id == task_id:
                            cls.queue.remove(spec)
                            self._fail_spec(spec, {
                                "type": "TaskCancelledError",
                                "message": f"task {spec.name} cancelled"})
                            return True
            for lease in list(self._by_id.values()):
                spec = lease.inflight.get(task_id)
                if spec is None:
                    continue
                self._cancelled[task_id] = force
                spec.max_retries = 0  # never retry a cancelled task
                if spec in lease.buf:
                    # Never sent to the worker: unbuffer and fail immediately
                    # (reference cancels pre-dispatch tasks synchronously).
                    # Applies to force too — killing the worker for a spec it
                    # never received would only hurt innocent neighbors.
                    lease.buf.remove(spec)
                    lease.inflight.pop(task_id, None)
                    self._cancelled.pop(task_id, None)
                    self._fail_spec(spec, {"type": "TaskCancelledError",
                                           "message": f"task {spec.name} cancelled"})
                elif force:
                    # Kill the worker, but do NOT requeue pipelined neighbors
                    # yet: they are requeued (attempt intact) by _lease_failed
                    # once the death is actually observed, so a neighbor can
                    # never run twice concurrently. Setting kill_target takes
                    # the lease out of _pump rotation immediately.
                    lease.kill_target = task_id
                    asyncio.ensure_future(
                        self._a_kill_for_cancel(lease, task_id))
                else:
                    # Already on the worker (queued or executing there).
                    # Don't guess the outcome: push the cancel and let the
                    # worker's tasks_done report decide — a value if the task
                    # wins the race (reference: ray.cancel losing the race
                    # delivers the value), a TaskCancelledError if the
                    # interrupt/skip wins.
                    if lease.conn is not None:
                        asyncio.ensure_future(
                            lease.conn.push("cancel", task_id=task_id))
                return True
            return False

        return self.w.io.run(_go())

    async def _a_kill_for_cancel(self, lease: _Lease, task_id: str):
        """Deliver a force-cancel kill, then make sure the doomed state
        resolves: a lease must never stay out of _pump rotation forever.

        - kill delivered → wait (bounded) for the death to arrive as a conn
          close; if it never does (kill push lost downstream), declare the
          lease failed ourselves so the class unblocks.
        - kill undeliverable (lease already torn down, controller blip) →
          un-doom: force cancel is best-effort in the reference too — the
          task then simply runs to completion and tasks_done decides the
          ref's outcome."""
        delivered = False
        for attempt in range(2):
            try:
                rep = await self.w.controller.call(
                    "kill_leased_worker", worker_id=lease.worker_id)
            except Exception:
                await asyncio.sleep(0.2)
                continue
            delivered = bool(rep.get("killed"))
            break
        # Grace period even when undeliverable: a concurrent kill (second
        # force-cancel on the same lease) may already be felling the worker.
        deadline = time.monotonic() + (10.0 if delivered else 1.0)
        while not lease.dead and time.monotonic() < deadline:
            await asyncio.sleep(0.05)
        if lease.dead:
            return
        if delivered:
            self._lease_failed(lease, release=False)
        elif lease.kill_target == task_id:
            lease.kill_target = None
            self._pump(lease.cls)

    # ------------------------------------------------------ lease returns
    async def _a_idle_loop(self):
        while not self._shutdown:
            await asyncio.sleep(0.25)
            now = time.monotonic()
            to_return = []
            for cls in self.classes.values():
                if cls.queue:
                    continue
                for lease in list(cls.leases.values()):
                    if (not lease.dead and not lease.inflight and not lease.buf
                            and now - lease.idle_since > IDLE_RETURN_S):
                        lease.dead = True
                        cls.leases.pop(lease.lease_id, None)
                        self._by_id.pop(lease.lease_id, None)
                        to_return.append(lease)
            if to_return:
                for lease in to_return:
                    if lease.conn is not None:
                        self._by_conn.pop(lease.conn, None)
                        try:
                            await lease.conn.close()
                        except Exception:
                            pass
                await self._a_return([l.lease_id for l in to_return])

    def reassert(self):
        """After a controller restart: re-declare every live lease so the
        new controller can rebuild its lease table + resource accounting
        (reference: raylets report held leases when the GCS restarts).
        Runs on the IO loop (called from the reconnect coroutine)."""
        entries = []
        for lease in self._by_id.values():
            if lease.dead:
                continue
            entries.append({
                "lease_id": lease.lease_id,
                "worker_id": lease.worker_id,
                "node_id": lease.node_id,
                "resources": lease.cls.resources,
                "strategy": lease.cls.strategy,
            })
        if entries:
            asyncio.ensure_future(self.w.controller.push(
                "reassert_leases", leases=entries,
                owner_id=self.w.worker_id))

    def on_need_resources(self):
        """Controller has demand it can't place: return idle leases now."""
        self.w.io.spawn(self._a_return_idle())

    async def _a_return_idle(self):
        to_return = []
        for cls in self.classes.values():
            if cls.queue:
                continue
            for lease in list(cls.leases.values()):
                if not lease.dead and not lease.inflight and not lease.buf:
                    lease.dead = True
                    cls.leases.pop(lease.lease_id, None)
                    self._by_id.pop(lease.lease_id, None)
                    if lease.conn is not None:
                        self._by_conn.pop(lease.conn, None)
                        try:
                            await lease.conn.close()
                        except Exception:
                            pass
                    to_return.append(lease.lease_id)
        if to_return:
            await self._a_return(to_return)

    async def _a_return(self, lease_ids: list[str]):
        try:
            await self.w.controller.call("return_leases", lease_ids=lease_ids)
        except Exception:
            pass

    def shutdown(self):
        self._shutdown = True
        ids = list(self._by_id)
        if ids:
            try:
                self.w.io.run(self._a_return(ids), timeout=2)
            except Exception:
                pass
