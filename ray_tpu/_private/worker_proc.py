"""Worker process entrypoint: executes tasks and hosts actors.

Parity target: the reference's task execution path — TaskReceiver
(core_worker/transport/task_receiver.h:51) + the Cython callback chain
(_raylet.pyx:2268 task_execution_handler ->
execute_task_with_cancellation_handler :2078): deserialize args, run the user
function, serialize/store returns (small inline, large to the shm store).
Actor calls arrive directly from callers on this process's RPC server
(reference direct actor transport) and execute in arrival order on the single
execution thread (reference sequential_actor_submit_queue.h).
"""

from __future__ import annotations

import asyncio
import ctypes
import inspect
import logging
import os
import queue
import sys
import threading
import time
import traceback

from ray_tpu._private import device_store, rpc, watchdog
from ray_tpu._private import telemetry as _telemetry
from ray_tpu._private import tracing as _tracing
from ray_tpu._private import runtime_env as _rtenv
from ray_tpu._private.rtconfig import CONFIG
from ray_tpu._private.serialization import dumps_oob, serialize
from ray_tpu._private.task_spec import ACTOR_CREATE, ACTOR_TASK, NORMAL, STREAMING, TaskSpec
from ray_tpu._private.worker import ObjectRef, Worker, set_global_worker

logger = logging.getLogger(__name__)


class _BatchPusher:
    """Thread-safe coalescing pusher: .add() from any thread, frames drain on
    the connection's loop — bursts of per-task messages ride few frames
    (mirrors the submit-side flusher in Worker._a_flush_submits)."""

    def __init__(self, conn, method: str, field: str):
        self.conn = conn
        self.method = method
        self.field = field
        self._buf: list = []
        self._lock = threading.Lock()
        self._flushing = False

    def add(self, item):
        with self._lock:
            self._buf.append(item)
            if self._flushing:
                return
            self._flushing = True
        asyncio.run_coroutine_threadsafe(self._a_flush(), self.conn.loop)

    async def _a_flush(self):
        while True:
            with self._lock:
                batch = self._buf
                self._buf = []
                if not batch:
                    self._flushing = False
                    return
            try:
                await self.conn.push(self.method, **{self.field: batch})
            except Exception:
                with self._lock:
                    self._flushing = False
                return  # peer gone; owner-side failure handling takes over


class WorkerProc:
    def __init__(self):
        self.worker_id = os.environ["RT_WORKER_ID"]
        self.node_id = os.environ["RT_NODE_ID"]
        self.session = os.environ["RT_SESSION"]
        chost, cport = os.environ["RT_CONTROLLER"].rsplit(":", 1)
        ahost, aport = os.environ["RT_AGENT"].rsplit(":", 1)
        self.agent_addr = (ahost, int(aport))
        self.worker = Worker(
            mode="worker",
            session_id=self.session,
            controller_addr=(chost, int(cport)),
            node_id=self.node_id,
            agent_addr=self.agent_addr,
            worker_id=self.worker_id,
        )
        self.exec_queue: "queue.Queue" = queue.Queue()
        self.agent_conn: rpc.Connection | None = None
        self.actor_instance = None
        self._method_cache: dict = {}  # method name -> (bound method, is_coro)
        self.actor_id: str | None = None
        self.actor_max_concurrency = 1
        self.actor_concurrency_groups: dict = {}
        self._group_pools: dict = {}
        self._group_budgets: dict = {}
        self._actor_pool = None  # ThreadPoolExecutor for threaded actors
        self._actor_loop = None  # EventLoopThread for async actors
        self._actor_sem: asyncio.Semaphore | None = None
        self._exec_thread_ident: int | None = None
        self._current_task_id: str | None = None
        self._cancel_requested: set[str] = set()  # cancels that beat the task
        # Leased-path specs accepted but not yet started: task_id -> (spec,
        # conn). Lets a cancel that arrives while the exec thread is blocked
        # in an earlier task report the cancellation immediately.
        self._pending_ltasks: dict = {}
        # Owner-failover bookkeeping: when a lease holder's connection
        # closes, its not-yet-started specs are skipped (the owner re-routes
        # them through the controller — running them here would
        # double-execute) and the spec executing RIGHT NOW is reported to
        # the node agent as `ltask_running` so a failover re-dispatch of the
        # same id parks on the agent's dedup record. The lock makes
        # "pending vs executing" atomic against the prune.
        self._skip_ltasks: set[str] = set()
        self._ltask_lock = threading.Lock()
        self._current_ltask: tuple | None = None  # (task_id, attempt, conn)
        # conn -> deque of recently completed direct-path reply payloads:
        # a push "succeeds" once buffered, so a connection dying right
        # after a completion may strand the reply — the prune republishes
        # these to the agent's dedup table so the owner's failover
        # re-dispatch resolves from the record instead of re-executing.
        self._recent_ltasks: dict = {}
        self._done_pushers: dict = {}  # owner conn -> _BatchPusher
        # Streaming generators (executor side): per-conn item pushers and
        # the consumer-ack table driving backpressure.
        self._gen_pushers: dict = {}  # owner conn -> _BatchPusher
        self._gen_acks: dict[str, int] = {}  # task_id -> items consumed
        self._gen_closed: set[str] = set()  # consumer abandoned the stream
        self._gen_cond = threading.Condition()
        self._prefetch_pool = None  # lazy: arg pre-localization threads
        self._event_seq = 0  # event sampling counter (high-rate shedding)
        self._event_win_start = 0.0
        self._event_win_count = 0
        self._advertise_pusher: _BatchPusher | None = None
        # Compiled-DAG loop threads attached to this actor: dag tag ->
        # list of stop events (one per loop; a dag may bind several of
        # this actor's methods). `__rt_dag_cancel__` sets them so a loop
        # parked on a dead upstream's channel exits promptly at teardown.
        self._dag_stops: dict[str, list] = {}
        self._pins_flagged = False  # last device_pins state told to the agent
        self._pins_lock = threading.Lock()  # orders flag updates vs pushes
        self._pid = os.getpid()  # cached: one event record per task must
        # not pay a getpid syscall (worker procs never fork-and-continue)
        # Stall watchdog (README "Stall detection & watchdogs"): started in
        # start() iff any RT_STALL_* stage is enabled. _timed_out marks
        # (task_id, attempt) pairs whose per-attempt timeout_s deadline
        # fired, so the resulting KeyboardInterrupt surfaces as a RETRYABLE
        # TaskTimeoutError instead of a cancellation.
        self._watchdog: watchdog.Watchdog | None = None
        self._timed_out: set[tuple] = set()
        self._current_attempt: int = 0
        self._running = True

    # ------------------------------------------------------------ startup
    def start(self):
        self.worker.connect()
        set_global_worker(self.worker)
        self.worker.actor_push_handler = self._on_actor_push
        self.worker.actor_batch_handler = self._on_actor_batch
        self.worker.task_push_handler = self._on_task_push
        self.worker.task_batch_handler = self._on_task_batch
        self.worker.task_cancel_handler = self._cancel_current
        self.worker.gen_ack_handler = self._on_gen_ack
        self.worker.gen_close_handler = self._on_gen_close
        # Every pin/unpin in this process — task/actor returns, put()s and
        # dref-arg promotions made INSIDE executing user code alike —
        # reports 0<->nonzero residency to the agent (idle-reap exemption).
        device_store.set_pins_listener(self._report_device_pins)

        def _rebind_ctrl_pushers():
            # Controller reconnected under us: the batched pushers hold the
            # OLD (dead) connection — rebind them or every later advertise
            # and task event silently vanishes.
            self._advertise_pusher = _BatchPusher(
                self.worker.controller, "register_puts", "items")
            self._event_pusher = _BatchPusher(
                self.worker.controller, "task_events", "events")

        self.worker.ctrl_reconnected_handler = _rebind_ctrl_pushers

        # Long-lived pool workers serve many lease holders; drop a holder's
        # batched reply pushers when its connection goes away.
        def _prune(conn):
            self._done_pushers.pop(conn, None)
            self._gen_pushers.pop(conn, None)
            # Owner failover: specs from this holder that haven't started
            # must never run here (the owner re-submits them through the
            # controller); the one executing right now is flagged to the
            # agent so the failover re-dispatch dedups on it.
            running = None
            with self._ltask_lock:
                for tid, (spec_, c) in list(self._pending_ltasks.items()):
                    if c is conn:
                        self._pending_ltasks.pop(tid, None)
                        self._skip_ltasks.add(tid)
                cur = self._current_ltask
                if cur is not None and cur[2] is conn:
                    running = cur[:2]
            if running is not None and self.agent_conn is not None:
                try:
                    self.agent_conn.push_threadsafe(
                        "ltask_running", task_id=running[0],
                        attempt=running[1], worker_id=self.worker_id)
                except Exception:
                    pass
            with self._ltask_lock:
                recent = self._recent_ltasks.pop(conn, None)
            if recent:
                self._report_orphaned(list(recent))
            with self._gen_cond:
                self._gen_cond.notify_all()  # unblock backpressure waits

        self.worker.server_close_handler = _prune
        self._advertise_pusher = _BatchPusher(
            self.worker.controller, "register_puts", "items")
        # Task events -> controller (reference task_event_buffer.h role):
        # one-way batched frames feeding the timeline + state APIs.
        self._event_pusher = _BatchPusher(
            self.worker.controller, "task_events", "events")

        async def _join_agent():
            self.agent_conn = await rpc.connect(
                *self.agent_addr,
                on_request=self._on_agent_request,
                on_push=self._on_agent_push,
                on_close=lambda c: os._exit(0) if self._running else None,
            )
            await self.agent_conn.call(
                "register_worker", worker_id=self.worker_id, address=self.worker.server_addr
            )

        self.worker.io.run(_join_agent(), timeout=CONFIG.connect_timeout_s)
        # Telemetry sampler (README "Telemetry & profiling"): device-side
        # series (jax HBM, compile events, device-object bytes) pushed to
        # the agent each tick. RT_TELEMETRY_INTERVAL_S unset => no thread,
        # nothing pushed — byte-identical off, pinned by test.
        if _telemetry.interval_s() > 0:
            self._telem_sampler = _telemetry.WorkerSampler(
                push=lambda series: self.agent_conn.push_threadsafe(
                    "worker_telemetry", worker_id=self.worker_id,
                    series=series),
                interval=_telemetry.interval_s())
            self._telem_sampler.start()
        # Stall watchdog: monitors every executing task's progress beacon
        # and walks the warn -> dump -> kill ladder through the node agent.
        # With all RT_STALL_* stages unset, start() is a no-op (no thread,
        # no beacons) — escalation-off behavior is byte-identical.
        self._watchdog = watchdog.Watchdog(
            worker_id=self.worker_id, node_id=self.node_id,
            session_id=self.session, on_report=self._push_stall_report,
            on_beacon=self._push_beacon)
        self._watchdog.start()

    def _push_stall_report(self, report: dict) -> bool:
        """Escalation stage crossed (runs on the watchdog thread): hand the
        StallReport to the node agent — it owns stack capture (its per-pid
        dump machinery), the storage-plane flight dump, and the kill.
        Returns False when the hand-off provably failed so the watchdog
        retries the stage next tick instead of marking it emitted."""
        if self.agent_conn is None or self.agent_conn.closed:
            return False
        try:
            self.agent_conn.push_threadsafe("stall_report", report=report)
            return True
        except Exception:
            return False

    def _push_beacon(self, task_id, silence: float):
        """Per-tick progress beacon to the agent. Beacons STOPPING while a
        task executes is itself a signal: the agent-side backstop escalates
        a worker too wedged (GIL held in native code) to self-report."""
        if self.agent_conn is None:
            return
        try:
            self.agent_conn.push_threadsafe(
                "watchdog_beacon", worker_id=self.worker_id,
                task_id=task_id, silence=round(silence, 3))
        except Exception:
            pass

    # ------------------------------------------------- per-attempt timeouts
    def _arm_task_timeout(self, spec: TaskSpec):
        """@remote(timeout_s=): arm the per-attempt execution deadline.
        Enforced HERE (worker-side) so a spinning task is interrupted even
        when its owner is gone; fires the same SIGINT path as cancel, but
        the _timed_out marker reroutes the interrupt into a RETRYABLE
        TaskTimeoutError (system failure under max_retries)."""
        t = getattr(spec, "timeout_s", None)
        if not t or t <= 0:
            return None
        ident = threading.get_ident()

        def _fire():
            # The task may have finished while the timer was in flight: only
            # interrupt the attempt the timer was armed for.
            if (self._current_task_id != spec.task_id
                    or self._current_attempt != spec.attempt):
                return
            self._timed_out.add((spec.task_id, spec.attempt))
            watchdog.record("task_timeout",
                            f"{spec.name} a{spec.attempt} > {t}s")
            try:
                from ray_tpu.util import metrics as _metrics

                _metrics.TASK_TIMEOUTS.inc(1)
            except Exception:
                pass
            if ident == threading.main_thread().ident:
                import signal

                os.kill(os.getpid(), signal.SIGINT)
            else:
                ctypes.pythonapi.PyThreadState_SetAsyncExc(
                    ctypes.c_ulong(ident), ctypes.py_object(KeyboardInterrupt))

        timer = threading.Timer(t, _fire)
        timer.daemon = True
        timer.start()
        return timer

    def _consume_timeout(self, spec: TaskSpec, e: BaseException):
        """Returns (error_blob, retryable) when the interrupt was this
        attempt's deadline firing, else None."""
        if not isinstance(e, KeyboardInterrupt):
            return None
        if (spec.task_id, spec.attempt) not in self._timed_out:
            return None
        self._timed_out.discard((spec.task_id, spec.attempt))
        h, bufs = dumps_oob({
            "type": "TaskTimeoutError",
            "message": f"task {spec.name} (attempt {spec.attempt}) exceeded "
                       f"its per-attempt timeout of {spec.timeout_s}s"})
        return [h, *bufs], True

    async def _on_agent_request(self, conn, method, a):
        """Agent->worker requests (the heartbeat/telemetry plane's only
        request path; execution orders stay pushes)."""
        if method == "profile":
            # On-demand capture (README "Telemetry & profiling"). Runs on
            # an executor thread: the capture loop sleeps between samples,
            # and this IO loop keeps carrying beacons/replies meanwhile —
            # which is exactly why a busy worker can be profiled live.
            mode = a.get("mode") or "cpu"
            seconds = _telemetry.clamp_profile_seconds(a.get("seconds"))
            loop = asyncio.get_running_loop()
            if mode == "cpu":
                hz = a.get("hz")
                return await loop.run_in_executor(
                    None, lambda: _telemetry.sample_profile(
                        seconds, int(hz) if hz else None))
            if mode == "jax":
                return await loop.run_in_executor(
                    None, lambda: _telemetry.jax_profile(seconds))
            raise rpc.RpcError(f"unknown profile mode {mode!r}")
        raise rpc.RpcError(f"worker: unknown agent method {method}")

    async def _on_agent_push(self, conn, method, a):
        if method == "execute":
            self.exec_queue.put(("task", a["spec"], None))
        elif method == "cancel":
            self._cancel_current(a["task_id"])
        elif method == "exit":
            self._running = False
            self.exec_queue.put(("exit", None, None))

    def _on_task_push(self, conn, spec: TaskSpec):
        """Direct-path spec from a lease holder (runs on the IO loop)."""
        self._pending_ltasks[spec.task_id] = (spec, conn)
        self.exec_queue.put(("ltask", spec, conn))
        self._prefetch_args(spec)

    def _on_task_batch(self, conn, specs: list):
        """A whole coalesced exec_tasks frame rides ONE exec-queue item."""
        for spec in specs:
            self._pending_ltasks[spec.task_id] = (spec, conn)
        self.exec_queue.put(("ltask_batch", specs, conn))
        for spec in specs:
            self._prefetch_args(spec)

    def _prefetch_args(self, spec: TaskSpec):
        """Pre-localize ref arguments while the spec waits in the exec queue
        (reference dependency_manager.h:55 localizes args BEFORE dispatch;
        without this, fetches serialize inside the task's execution slot)."""
        oids = spec.ref_arg_oids()
        if not oids:
            return
        if self._prefetch_pool is None:
            from concurrent.futures import ThreadPoolExecutor

            self._prefetch_pool = ThreadPoolExecutor(
                max_workers=2, thread_name_prefix="rt-prefetch")

        def _fetch(oid):
            try:
                # Localize bytes only (no deserialization — decode_args does
                # that once, in the exec slot); bounded so a never-resolving
                # ref can't wedge the 2-thread pool forever.
                self.worker.prefetch_object(oid, timeout=120.0)
            except Exception:
                pass

        for oid in oids:
            self._prefetch_pool.submit(_fetch, oid)

    def _report_device_pins(self):
        """device_store pins listener: tell the agent whether this worker
        currently pins device objects (0<->nonzero transitions only) —
        pinned pool workers are exempt from the idle reap, they ARE the
        storage for those objects. The lock orders the stats read, flag
        update and push: a pin on the exec thread racing a device_free on
        the IO thread must not publish transitions out of order (a stale
        trailing pinned=True would exempt an empty worker forever)."""
        if self.agent_conn is None:
            return
        with self._pins_lock:
            pinned = device_store.table_stats()["count"] > 0
            if pinned == self._pins_flagged:
                return
            self._pins_flagged = pinned
            try:
                self.agent_conn.push_threadsafe(
                    "device_pins", worker_id=self.worker_id, pinned=pinned)
            except Exception:
                pass

    def _pusher_for(self, conn) -> "_BatchPusher | None":
        """Per-connection batched reply pusher; None once the holder's
        connection has closed (never re-create an entry for a dead conn —
        its on_close already fired and nothing would ever prune it again)."""
        pusher = self._done_pushers.get(conn)
        if pusher is None and not conn.closed:
            pusher = self._done_pushers[conn] = _BatchPusher(conn, "tasks_done", "done")
            if conn.closed:
                # Raced with the close between the check and the insert: the
                # on_close prune may have already run and found nothing, so
                # prune our own insert (the returned pusher still works — its
                # flush just fails against the dead conn).
                self._done_pushers.pop(conn, None)
        return pusher

    def _on_actor_push(self, conn, spec: TaskSpec):
        """Pipelined actor call (runs on the IO loop): execute in arrival
        order, reply via the per-connection batched pusher."""
        self.exec_queue.put(("actor_batch", [spec], self._pusher_for(conn)))

    def _on_actor_batch(self, conn, specs: list):
        """A whole coalesced actor_calls frame rides ONE exec-queue item:
        at n:n call rates the per-call queue put/get + condition notify was
        a measurable share of the worker's core budget."""
        self.exec_queue.put(("actor_batch", specs, self._pusher_for(conn)))

    def _cancel_current(self, task_id: str):
        """Non-force cancel: raise KeyboardInterrupt in the executing thread
        (reference: ray.cancel() delivers KeyboardInterrupt to the worker's
        main thread, _raylet.pyx execute_task_with_cancellation_handler).
        The exec thread is this process's main thread, so a SIGINT interrupts
        even blocking syscalls (e.g. time.sleep); PyThreadState_SetAsyncExc
        would only fire at the next bytecode boundary."""
        if self._current_task_id != task_id or self._exec_thread_ident is None:
            # The execute push may still be queued ahead of us: remember the
            # cancel so the exec loop aborts the task before running it.
            self._cancel_requested.add(task_id)
            ent = self._pending_ltasks.pop(task_id, None)
            if ent is not None:
                # The spec provably hasn't started and the exec thread may be
                # blocked in a long task ahead of it — report the
                # cancellation NOW (we're on the IO loop) so the owner isn't
                # held hostage by the pipeline head (reference cancels
                # pre-dispatch tasks promptly). The exec loop's own
                # before-start abort later reports again; the owner ignores
                # the duplicate (spec already popped from inflight).
                spec, conn = ent
                h, bufs = dumps_oob({"type": "TaskCancelledError",
                                     "message": f"task {spec.name} cancelled"})
                pusher = self._pusher_for(conn)
                if pusher is not None:
                    pusher.add((spec.task_id, spec.attempt,  # rtcheck: wire=tasks_done.item
                                [(oid, None, 0, None)
                                 for oid in spec.return_object_ids()],
                                [h, *bufs], False, None))
            return
        if self._exec_thread_ident == threading.main_thread().ident:
            import signal

            os.kill(os.getpid(), signal.SIGINT)
        else:
            ctypes.pythonapi.PyThreadState_SetAsyncExc(
                ctypes.c_ulong(self._exec_thread_ident), ctypes.py_object(KeyboardInterrupt))

    # ---------------------------------------------------------- exec loop
    def run(self):
        self._exec_thread_ident = threading.get_ident()
        while self._running:
            try:
                kind, spec, reply_slot = self.exec_queue.get()
            except KeyboardInterrupt:
                continue  # late cancel signal; its task already finished
            if kind == "exit":
                break
            try:
                if kind == "ltask":
                    self._execute_leased_task(spec, reply_slot)
                elif kind == "ltask_batch":
                    for sp in spec:
                        self._execute_leased_task(sp, reply_slot)
                elif kind == "actor_batch":
                    pusher = reply_slot
                    for sp in spec:
                        self._dispatch_actor_task(sp, pusher)
                elif spec.kind == ACTOR_TASK:
                    self._dispatch_actor_task(spec, None)
                else:
                    self._execute_task(spec)
            except BaseException as e:
                # A late cancel/timeout SIGINT (KeyboardInterrupt) escaping
                # the per-task guards must not fell the exec loop — the
                # worker keeps draining its queue; attribute what survived.
                print(f"exec loop survived {type(e).__name__} "
                      f"(task dispatch)", file=sys.stderr)
                traceback.print_exc()
        self.worker.disconnect()

    def _dispatch_actor_task(self, spec: TaskSpec, reply_slot):
        """Route an actor call to the right executor: async actors run
        coroutine methods on a dedicated asyncio loop bounded by a
        max_concurrency semaphore; threaded actors (max_concurrency>1) and
        methods in declared concurrency groups use per-group thread pools;
        default actors execute inline in arrival order (reference
        concurrency_group_manager.h + fiber.h for async actors)."""
        if spec.method_name == "__rt_dag_loop__":
            # Compiled-graph execution loop attached to this EXISTING actor
            # (reference compiled_dag_node: bound actors host channel
            # loops). Runs on its OWN thread so normal method calls keep
            # flowing; the reply resolves when the DAG tears down.
            self._start_dag_loop(spec, reply_slot)
            return
        if spec.method_name == "__rt_dag_cancel__":
            # Compiled-DAG teardown: cancel this actor's loop threads for
            # the named dag (their upstream may be dead, so the graceful
            # stop token may never arrive through the channels).
            error_blob = None
            try:
                (desc,), _ = self.worker.decode_args(spec.args, spec.kwargs)
                for ev in list(self._dag_stops.get(desc.get("tag"), ())):
                    ev.set()
            except BaseException as e:  # noqa: BLE001 - reply must go out
                error_blob = self._make_error_blob(spec, e)
            self._reply_value(reply_slot, spec.task_id,
                              self._finish_actor_task(spec, None, error_blob))
            return
        ent = self._method_cache.get(spec.method_name)
        if ent is None and self.actor_instance is not None:
            m = getattr(self.actor_instance, spec.method_name, None)
            group = getattr(m, "_rt_concurrency_group", None) if m is not None else None
            if group is not None and group not in self.actor_concurrency_groups:
                group = None  # undeclared group: fall back to default routing
            ent = self._method_cache[spec.method_name] = (
                m, m is not None and (inspect.iscoroutinefunction(m)
                                      or inspect.isasyncgenfunction(m)), group)
        group = ent[2] if ent is not None else None
        # Streaming item reports ride the caller's connection (the one the
        # reply pusher is bound to).
        conn = reply_slot.conn if reply_slot is not None else None
        if ent is not None and ent[1]:
            self._ensure_actor_loop()
            cf = asyncio.run_coroutine_threadsafe(
                self._a_exec_actor_task(spec, group, conn), self._actor_loop.loop)
            cf.add_done_callback(
                lambda f, rs=reply_slot, tid=spec.task_id: self._reply_future(rs, tid, f))
        elif group is not None:
            cf = self._group_pool(group).submit(
                self._execute_group_task, spec, group, conn)
            cf.add_done_callback(
                lambda f, rs=reply_slot, tid=spec.task_id: self._reply_future(rs, tid, f))
        elif self.actor_max_concurrency > 1:
            if self._actor_pool is None:
                from concurrent.futures import ThreadPoolExecutor

                self._actor_pool = ThreadPoolExecutor(max_workers=self.actor_max_concurrency,
                                                      thread_name_prefix="rt-actor")
            cf = self._actor_pool.submit(self._execute_actor_task, spec, conn)
            cf.add_done_callback(
                lambda f, rs=reply_slot, tid=spec.task_id: self._reply_future(rs, tid, f))
        else:
            reply = self._execute_actor_task(spec, conn)
            self._reply_value(reply_slot, spec.task_id, reply)

    def _start_dag_loop(self, spec: TaskSpec, reply_slot):
        """Spawn the compiled-DAG stage loop thread for this actor."""
        def _run():
            error_blob = None
            value = None
            stop = threading.Event()
            tag = None
            try:
                from ray_tpu.dag import run_stage_loop

                (desc,), _ = self.worker.decode_args(spec.args, spec.kwargs)
                tag = desc.get("tag")
                if tag:
                    self._dag_stops.setdefault(tag, []).append(stop)
                method = getattr(self.actor_instance, desc["method"])
                value = run_stage_loop(
                    method, desc["in_specs"], desc["out_names"],
                    desc.get("kwargs") or {}, desc["size"],
                    stage=desc.get("stage", "stage"), stop=stop)
            except BaseException as e:  # noqa: BLE001
                error_blob = self._make_error_blob(spec, e)
            finally:
                if tag:
                    evs = self._dag_stops.get(tag)
                    if evs is not None:
                        try:
                            evs.remove(stop)
                        except ValueError:
                            pass
                        if not evs:
                            self._dag_stops.pop(tag, None)
            reply = self._finish_actor_task(spec, value, error_blob)
            self._reply_value(reply_slot, spec.task_id, reply)

        threading.Thread(target=_run, daemon=True,
                         name="rt-dag-loop").start()

    def _group_pool(self, group: str):
        """Thread pool for one declared concurrency group (reference
        concurrency_group_manager.h: each group owns its executor, so a
        saturated group never blocks the others)."""
        pool = self._group_pools.get(group)
        if pool is None:
            from concurrent.futures import ThreadPoolExecutor

            limit = max(1, int(self.actor_concurrency_groups.get(group, 1)))
            pool = self._group_pools[group] = ThreadPoolExecutor(
                max_workers=limit, thread_name_prefix=f"rt-cg-{group}")
        return pool

    def _group_budget(self, group: str) -> threading.Semaphore:
        """ONE concurrency budget per group shared by the sync (thread
        pool) and async (actor loop) execution paths — a group mixing sync
        and async methods must still honor its declared limit."""
        sem = self._group_budgets.get(group)
        if sem is None:
            limit = max(1, int(self.actor_concurrency_groups.get(group, 1)))
            sem = self._group_budgets[group] = threading.Semaphore(limit)
        return sem

    def _execute_group_task(self, spec: TaskSpec, group: str, conn=None):
        sem = self._group_budget(group)
        sem.acquire()  # pool thread; blocking is fine
        try:
            return self._execute_actor_task(spec, conn)
        finally:
            sem.release()

    def _ensure_actor_loop(self):
        if self._actor_loop is None:
            self._actor_loop = rpc.EventLoopThread(name="rt-actor-loop")

            async def _mk_sem():
                return asyncio.Semaphore(max(1, self.actor_max_concurrency))

            self._actor_sem = self._actor_loop.run(_mk_sem())

    async def _a_acquire_group(self, group: str | None):
        """Acquire the shared group budget from the actor loop without
        blocking it (short poll; group methods are coarse-grained). None ->
        the whole-actor max_concurrency semaphore."""
        if group is None:
            await self._actor_sem.acquire()
            return self._actor_sem.release
        sem = self._group_budget(group)
        while not sem.acquire(blocking=False):
            await asyncio.sleep(0.002)
        return sem.release

    async def _a_exec_actor_task(self, spec: TaskSpec, group: str | None = None,
                                 conn=None) -> dict:
        release = await self._a_acquire_group(group)
        try:
            return await self._a_exec_actor_task_inner(spec, conn)
        finally:
            release()

    async def _a_exec_actor_task_inner(self, spec: TaskSpec, conn=None) -> dict:
        error_blob = None
        value = None
        streaming = spec.num_returns == STREAMING
        gen_count = 0
        # Execute span + context for async actor methods: set inside this
        # coroutine, the contextvar scopes to it — everything the method
        # does (engine submits, nested calls, streamed iteration) chains
        # under the execute span without leaking to sibling requests.
        trace_h = _tracing.task_execute_begin(spec)
        t0 = time.time()
        try:
            method = getattr(self.actor_instance, spec.method_name)
            args, kwargs = self.worker.decode_args(spec.args, spec.kwargs)
            r = method(*args, **kwargs)
            if hasattr(r, "__anext__"):
                if not streaming:
                    raise TypeError(
                        f"async generator method {spec.method_name!r} "
                        f"requires num_returns='streaming'")
                value = r
            else:
                value = await r
            if streaming:
                gen_count, gerr, _ = await self._a_stream_generator(
                    spec, value, conn)
                if gerr is not None:
                    error_blob = gerr
        except BaseException as e:  # noqa: BLE001
            error_blob = self._make_error_blob(spec, e)
        _tracing.task_execute_end(trace_h, ok=error_blob is None)
        self._record_event(spec, t0, time.time(), error_blob is None)
        if streaming:
            return {"results": self._package_stream_completion(
                spec, gen_count, error_blob), "error": error_blob}
        return self._finish_actor_task(spec, value, error_blob)

    def _reply_value(self, pusher, task_id: str, reply: dict):
        if pusher is not None:  # None once the holder's connection closed
            # Compact wire record (see _done_item): dict replies with five
            # constant keys cost ~2x the pickle of a tuple at n:n rates.
            pusher.add((task_id, 0, reply.get("results"), reply.get("error"),  # rtcheck: wire=tasks_done.item
                        False, reply.get("exec_failure")))

    def _reply_future(self, pusher, task_id: str, done_future):
        try:
            reply = done_future.result()
        except BaseException as e:  # executor infrastructure failure
            reply = {"results": [], "error": None, "exec_failure": str(e)}
        self._reply_value(pusher, task_id, reply)

    _EVENT_RATE_FULL = 500  # events/s below which everything records
    _EVENT_SAMPLE = 64      # 1/N sampling above the rate threshold

    def _record_event(self, spec: TaskSpec, start: float, end: float,
                      ok: bool):
        """Buffer one execution event (batched to the controller; feeds
        ray_tpu.timeline() and the state list APIs). ADAPTIVE shedding:
        everything records at ordinary rates (full timelines), but past
        _EVENT_RATE_FULL successful events/s this worker samples 1/N —
        at tens of thousands of calls/s the per-event dict + push costs a
        measurable third of the core budget (observed n:n actor bench
        14.5k -> 22.5k/s; the reference task_event_buffer likewise sheds
        load under pressure). Failures always record."""
        if ok:
            now = end
            if now - self._event_win_start >= 1.0:
                self._event_win_start = now
                self._event_win_count = 0
            self._event_win_count += 1
            if self._event_win_count > self._EVENT_RATE_FULL:
                self._event_seq += 1
                if self._event_seq % self._EVENT_SAMPLE:
                    return
        try:
            self._event_pusher.add({
                "task_id": spec.task_id, "name": spec.name,
                "kind": spec.kind, "attempt": spec.attempt,
                "start": start, "end": end, "ok": ok,
                "worker_id": self.worker_id, "node_id": self.node_id,
                "pid": self._pid,
            })
        except Exception:
            pass  # observability must never break execution

    # ------------------------------------------------ streaming generators
    def _on_gen_ack(self, task_id: str, consumed: int):
        with self._gen_cond:
            # Only update LIVE streams (registered by the stream loop): a
            # late ack landing after the stream's finally-pop must not
            # re-create the entry — long-lived workers would leak one dict
            # slot per streaming task served.
            if task_id in self._gen_acks and consumed > self._gen_acks[task_id]:
                self._gen_acks[task_id] = consumed
                self._gen_cond.notify_all()

    def _on_gen_close(self, task_id: str):
        """Owner dropped its ObjectRefGenerator: stop producing. This is the
        only stop path for actor-task streams (no lease/controller cancel
        reaches them) and it also unblocks a parked backpressure wait.
        Only LIVE streams are marked (same guard as _on_gen_ack): a close
        landing after the stream's finally would leak a set entry per
        abandoned stream in a long-lived worker. A close that beats the
        stream's start is re-sent by the owner on every later straggler
        item, so the live stream still learns of it."""
        with self._gen_cond:
            if task_id in self._gen_acks:
                self._gen_closed.add(task_id)
                self._gen_cond.notify_all()

    def _gen_pusher_for(self, conn) -> "_BatchPusher | None":
        pusher = self._gen_pushers.get(conn)
        if pusher is None and conn is not None and not conn.closed:
            pusher = self._gen_pushers[conn] = _BatchPusher(
                conn, "gen_items", "items")
            if conn.closed:
                # Raced with the close between the check and the insert (the
                # on_close prune may already have run and found nothing):
                # prune our own insert — same pattern as _pusher_for.
                self._gen_pushers.pop(conn, None)
        return pusher

    def _serialize_return(self, oid: str, value) -> tuple:
        """Serialize ONE return value into its wire/result tuple
        (oid, inline, size, holder): small inline, large into the node shm
        store with the agent as the advertised holder (it outlives workers).
        Shared by regular returns and streamed generator items so the inline
        threshold / detach / escaping-ref rules can never diverge."""
        if device_store.eligible(value):
            # Device object plane: pin the live array here instead of
            # copying it through the host store; the placeholder rides the
            # reply/advertise as the inline payload with this worker's
            # address as the device-location hint (README "Device objects").
            return device_store.pin_return(oid, value, self.worker)
        sobj = serialize(value, ref_class=ObjectRef)
        if sobj.contained_refs:
            # Returned refs escape to the caller here: refs THIS worker owns
            # (results of its own sub-calls) must reach the controller
            # before the borrower can possibly wait on them.
            self.worker._advertise_escaping(
                [r.hex() if isinstance(r, ObjectRef) else r
                 for r in sobj.contained_refs])
        size = sobj.total_bytes()
        if size <= CONFIG.max_inline_object_bytes:
            return (oid, [sobj.to_bytes()], size, None)
        self.worker.store.put_serialized(oid, sobj)
        # Drop the producer's mapping: the agent is the advertised holder,
        # and keeping it would pin freed pages until this worker exits
        # (same-host readers re-attach from the file).
        self.worker.store.detach(oid)
        return (oid, None, size, self.agent_addr)

    def _advert_item(self, oid: str, size, inline, holder, owner,
                     error) -> dict:
        """One register_put advertise record; device-plane results (pinned
        by _serialize_return) carry the plane marker so the controller can
        route frees and the producer-death lost sweep."""
        item = {"oid": oid, "size": size, "inline": inline,
                "holder": holder, "owner": owner, "error": error}
        if device_store.holds(oid):
            item.update(device_store.advert_fields(self.worker_id,
                                                   self.node_id))
        return item

    def _package_one(self, spec: TaskSpec, idx: int, value) -> tuple:
        """Package ONE yielded stream item, advertising shm items to the
        controller immediately so third-party borrowers can fetch."""
        oid = spec.task_id + idx.to_bytes(4, "little").hex()
        watchdog.report_progress()  # each yielded item IS progress
        result = self._serialize_return(oid, value)
        if result[3] is not None:
            # result[1] is None for host shm items and the placeholder for
            # device items — same shape as the non-streaming advertises.
            self._advertise_pusher.add(self._advert_item(
                oid, result[2], result[1], result[3], spec.owner_id, None))
        return result

    def _stream_generator(self, spec: TaskSpec, value, conn):
        """Drive a sync generator/iterable, reporting each item to the owner
        as it is yielded (reference ReportGeneratorItemReturns,
        core_worker.proto:478). Returns (count, error_blob, exception).
        Backpressure: pause once `generator_backpressure_items` items are
        unacknowledged (acks ride `gen_ack` pushes from the consumer)."""
        pusher = self._gen_pusher_for(conn)
        thresh = CONFIG.generator_backpressure_items
        tid = spec.task_id
        # iter() BEFORE registering as live: a non-iterable return raises
        # here, and registering first would leak the _gen_acks entry (the
        # finally below would never run).
        it = iter(value)
        with self._gen_cond:
            self._gen_acks[tid] = 0  # register as live (acks update only live streams)
        idx = 0
        try:
            for item in it:
                with self._gen_cond:
                    if tid in self._gen_closed:
                        break  # consumer abandoned the stream
                result = self._package_one(spec, idx, item)
                if pusher is not None:
                    pusher.add((tid, idx, result))
                idx += 1
                if thresh > 0 and idx % thresh == 0:
                    with self._gen_cond:
                        while (idx - self._gen_acks.get(tid, 0) >= thresh
                               and tid not in self._gen_closed
                               and conn is not None and not conn.closed):
                            self._gen_cond.wait(timeout=0.25)
            return idx, None, None
        except BaseException as e:  # noqa: BLE001 — user generator code
            return idx, self._make_error_blob(spec, e), e
        finally:
            close = getattr(it, "close", None)
            if close is not None:
                try:
                    close()  # run the generator's finally blocks
                except Exception:
                    pass
            with self._gen_cond:
                self._gen_acks.pop(tid, None)
                self._gen_closed.discard(tid)

    async def _a_stream_generator(self, spec: TaskSpec, value, conn):
        """Async flavor for async-generator actor methods (runs on the actor
        loop — backpressure waits must not block the loop)."""
        pusher = self._gen_pusher_for(conn)
        thresh = CONFIG.generator_backpressure_items
        tid = spec.task_id
        # iter() BEFORE registering as live (see _stream_generator).
        if not hasattr(value, "__anext__"):
            value = iter(value)
        with self._gen_cond:
            self._gen_acks[tid] = 0  # register as live
        idx = 0
        try:
            while True:
                if tid in self._gen_closed:
                    break  # consumer abandoned the stream
                try:
                    if hasattr(value, "__anext__"):
                        item = await value.__anext__()
                    else:
                        item = next(value)
                except (StopAsyncIteration, StopIteration):
                    break
                result = self._package_one(spec, idx, item)
                if pusher is not None:
                    pusher.add((tid, idx, result))
                idx += 1
                if thresh > 0 and idx % thresh == 0:
                    while (idx - self._gen_acks.get(tid, 0) >= thresh
                           and tid not in self._gen_closed
                           and conn is not None and not conn.closed):
                        await asyncio.sleep(0.005)
            return idx, None, None
        except BaseException as e:  # noqa: BLE001
            return idx, self._make_error_blob(spec, e), e
        finally:
            aclose = getattr(value, "aclose", None)
            if aclose is not None:
                try:
                    await aclose()
                except Exception:
                    pass
            with self._gen_cond:
                self._gen_acks.pop(tid, None)
                self._gen_closed.discard(tid)

    def _package_stream_completion(self, spec: TaskSpec, count: int,
                                   error_blob) -> list:
        """The streaming task's single declared return: the completion
        sentinel, resolving to the item count (or carrying the error)."""
        comp_oid = spec.return_object_ids()[0]
        if error_blob is not None:
            return [(comp_oid, None, 0, None)]
        sobj = serialize(count, ref_class=ObjectRef)
        return [(comp_oid, [sobj.to_bytes()], sobj.total_bytes(), None)]

    # ---------------------------------------------------------- execution
    def _package_results(self, spec: TaskSpec, value, error_blob):
        """Serialize return values: small inline, large into the node shm
        store with the agent as the advertised holder (it outlives workers)."""
        results = []
        oids = spec.return_object_ids()
        if error_blob is not None:
            for oid in oids:
                results.append((oid, None, 0, None))
            return results
        if spec.num_returns == 0:
            return results
        values = [value] if spec.num_returns == 1 else list(value)
        if spec.num_returns > 1 and len(values) != spec.num_returns:
            raise ValueError(
                f"task {spec.name} declared num_returns={spec.num_returns} "
                f"but returned {len(values)} values"
            )
        for oid, v in zip(oids, values):
            results.append(self._serialize_return(oid, v))
        return results

    def _make_error_blob(self, spec: TaskSpec, e: BaseException):
        if isinstance(e, KeyboardInterrupt):
            h, bufs = dumps_oob({"type": "TaskCancelledError",
                                 "message": f"task {spec.name} cancelled"})
            return [h, *bufs]
        tb = traceback.format_exc()
        cause_header = None
        try:
            cause_header, cause_bufs = dumps_oob(e)
            if cause_bufs:
                cause_header = None  # keep error blobs simple: no oob bufs
        except Exception:
            cause_header = None
        h, bufs = dumps_oob(
            {
                "type": "TaskError",
                "function_name": spec.name,
                "traceback": tb,
                "cause": cause_header,
            }
        )
        return [h, *bufs]

    @staticmethod
    def _exception_retryable(spec: TaskSpec, e: BaseException) -> bool:
        """retry_exceptions semantics (reference remote_function.py options):
        True -> any Exception retries; a list/tuple of types -> isinstance
        match; False/None -> user exceptions are final."""
        if isinstance(e, KeyboardInterrupt):
            return False  # cancellation is never retried
        rx = spec.retry_exceptions
        if rx is True:
            return isinstance(e, Exception)
        if isinstance(rx, (list, tuple)):
            return any(isinstance(e, t) for t in rx if isinstance(t, type))
        return False

    def _execute_task(self, spec: TaskSpec):
        """Outer shell: a cancel SIGINT can land in any crack of the inner
        body (e.g. the env-restore finally) — whatever happens, a task_done
        MUST reach the controller or the caller blocks and the agent counts
        the slot busy forever."""
        try:
            self._execute_task_inner(spec)
            return
        except KeyboardInterrupt:
            error_blob = self._make_error_blob(spec, KeyboardInterrupt())
        results = self._package_results(spec, None, error_blob)

        async def _report():
            await self.worker.controller.push(
                "task_done", task_id=spec.task_id, attempt=spec.attempt,
                results=results, error=error_blob, retryable=False, spec=None)
            if spec.kind == NORMAL:
                await self.agent_conn.push("worker_idle", worker_id=self.worker_id)

        for _ in range(2):
            try:
                self.worker.io.run(_report())
                break
            except KeyboardInterrupt:
                continue

    def _execute_task_inner(self, spec: TaskSpec):
        error_blob = None
        value = None
        retryable = False
        # Apply per-task env vars; restore after on pooled (non-actor)
        # workers so a reused worker doesn't leak the previous task's env
        # (reference keys the worker pool by runtime env, worker_pool.h:228).
        saved_env: dict[str, str | None] = {}
        env_vars = spec.runtime_env.get("env_vars") or {}
        for k, v in env_vars.items():
            saved_env[k] = os.environ.get(k)
            os.environ[k] = str(v)
        undo_env = lambda: None  # noqa: E731
        self._current_task_id = spec.task_id
        self._current_attempt = spec.attempt
        trace_h = _tracing.task_execute_begin(spec)
        watchdog.task_begin(spec.task_id, spec.name, spec.attempt, spec.kind,
                            trace_id=spec.trace[0] if spec.trace else None)
        timer = self._arm_task_timeout(spec)
        t0 = time.time()
        try:
            # Inside the try: a bad package (missing KV blob, corrupt zip)
            # must surface as a task error, not crash the worker loop.
            undo_env = _rtenv.apply(self.worker, spec.runtime_env)
            if spec.task_id in self._cancel_requested:
                self._cancel_requested.discard(spec.task_id)
                raise KeyboardInterrupt  # cancelled before it started
            if spec.kind == ACTOR_CREATE:
                cls = self.worker.load_function(spec.function_id)
                args, kwargs = self.worker.decode_args(spec.args, spec.kwargs)
                self.actor_instance = cls(*args, **kwargs)
                self._method_cache.clear()
                self.actor_id = spec.actor_id
                self.actor_max_concurrency = max(1, spec.max_concurrency)
                self.actor_concurrency_groups = dict(spec.concurrency_groups or {})
            else:
                if spec.num_returns == STREAMING:
                    raise RuntimeError(
                        "streaming generators are not supported on the "
                        "controller dispatch path (TPU tasks / "
                        "reconstruction); use the lease path or an actor")
                fn = self.worker.load_function(spec.function_id)
                args, kwargs = self.worker.decode_args(spec.args, spec.kwargs)
                value = fn(*args, **kwargs)
        except BaseException as e:  # noqa: BLE001 — user code may raise anything
            timed_out = self._consume_timeout(spec, e)
            if timed_out is not None:
                error_blob, retryable = timed_out
            else:
                error_blob = self._make_error_blob(spec, e)
                retryable = self._exception_retryable(spec, e)
            if spec.kind == ACTOR_CREATE:
                logger.error("actor __init__ failed:\n%s", traceback.format_exc())
        finally:
            if timer is not None:
                timer.cancel()
            self._timed_out.discard((spec.task_id, spec.attempt))
            self._current_task_id = None
            watchdog.task_end(error_blob is None)
            _tracing.task_execute_end(trace_h, ok=error_blob is None)
            self._record_event(spec, t0, time.time(), error_blob is None)
            if spec.kind != ACTOR_CREATE:  # dedicated actor procs keep their env
                undo_env()
                for k, old in saved_env.items():
                    if old is None:
                        os.environ.pop(k, None)
                    else:
                        os.environ[k] = old
        try:
            results = self._package_results(spec, value, error_blob)
        except KeyboardInterrupt:
            # Late cancel signal after user code finished: the result stands.
            results = self._package_results(spec, value, error_blob)
        except BaseException as e:
            error_blob = self._make_error_blob(spec, e)
            results = self._package_results(spec, None, error_blob)

        async def _report():
            payload = dict(task_id=spec.task_id, attempt=spec.attempt,
                           results=results, error=error_blob,
                           retryable=retryable, spec=None)
            if spec.kind == ACTOR_CREATE:
                payload["actor_address"] = self.worker.server_addr
            await self.worker.controller.push("task_done", **payload)
            if spec.kind == NORMAL:
                await self.agent_conn.push("worker_idle", worker_id=self.worker_id)

        for _ in range(2):  # a late cancel SIGINT must not lose the report
            try:
                self.worker.io.run(_report())
                break
            except KeyboardInterrupt:
                continue

    def _execute_leased_task(self, spec: TaskSpec, conn):
        """Direct-path execution: results go straight back to the lease
        holder over the connection the spec arrived on (batched), and are
        advertised to the controller's object directory in batched frames
        for third-party borrowers. No per-task agent involvement — the slot
        stays leased (reference: executing a PushNormalTask on a leased
        worker, task_receiver.h:51)."""
        with self._ltask_lock:
            if spec.task_id in self._skip_ltasks:
                # The holder's connection died before this spec started:
                # the owner fails it over to the controller path, so running
                # it here too would double-execute.
                self._skip_ltasks.discard(spec.task_id)
                return
            self._pending_ltasks.pop(spec.task_id, None)
            self._current_ltask = (spec.task_id, spec.attempt, conn)
        try:
            self._execute_leased_task_inner(spec, conn)
        except KeyboardInterrupt:
            # A cancel/timeout SIGINT can land in any crack the inner
            # body's own retry loops don't cover (e.g. the env-restore
            # finally, right as the task completed): the reply may never
            # have been delivered, and a lost reply hangs the owner's
            # get() forever. Send a best-effort outcome — if the real
            # reply already went out, the owner ignores this duplicate
            # (its inflight entry is gone).
            timed_out = (spec.task_id, spec.attempt) in self._timed_out
            self._timed_out.discard((spec.task_id, spec.attempt))
            if timed_out:
                h, bufs = dumps_oob({
                    "type": "TaskTimeoutError",
                    "message": f"task {spec.name} (attempt {spec.attempt}) "
                               f"exceeded its per-attempt timeout of "
                               f"{spec.timeout_s}s"})
                retryable = True
            else:
                h, bufs = dumps_oob({
                    "type": "TaskCancelledError",
                    "message": f"task {spec.name} cancelled"})
                retryable = False
            pusher = self._pusher_for(conn)
            if pusher is not None:
                pusher.add((spec.task_id, spec.attempt,  # rtcheck: wire=tasks_done.item
                            [(oid, None, 0, None)
                             for oid in spec.return_object_ids()],
                            [h, *bufs], retryable, None))
        finally:
            with self._ltask_lock:
                self._current_ltask = None

    def _report_orphaned(self, payloads):
        """Holder gone with these outcomes possibly undelivered: publish
        them to the node agent's dedup table (`ltask_done`) so the owner's
        failover re-dispatch resolves from the record instead of executing
        the task a second time."""
        if self.agent_conn is None:
            return
        for tid, attempt, results, error, retryable, _ in payloads:
            try:
                self.agent_conn.push_threadsafe(
                    "ltask_done", worker_id=self.worker_id, task_id=tid,
                    attempt=attempt, results=results, error=error,
                    retryable=retryable)
            except Exception:
                return

    def _execute_leased_task_inner(self, spec: TaskSpec, conn):
        error_blob = None
        value = None
        retryable = False
        streaming = spec.num_returns == STREAMING
        gen_count = 0
        saved_env: dict[str, str | None] = {}
        env_vars = spec.runtime_env.get("env_vars") or {}
        for k, v in env_vars.items():
            saved_env[k] = os.environ.get(k)
            os.environ[k] = str(v)
        undo_env = lambda: None  # noqa: E731
        self._current_task_id = spec.task_id
        self._current_attempt = spec.attempt
        trace_h = _tracing.task_execute_begin(spec)
        watchdog.task_begin(spec.task_id, spec.name, spec.attempt, spec.kind,
                            trace_id=spec.trace[0] if spec.trace else None)
        timer = self._arm_task_timeout(spec)
        t0 = time.time()
        try:
            undo_env = _rtenv.apply(self.worker, spec.runtime_env)
            if spec.task_id in self._cancel_requested:
                self._cancel_requested.discard(spec.task_id)
                raise KeyboardInterrupt  # cancelled before it started
            fn = self.worker.load_function(spec.function_id)
            args, kwargs = self.worker.decode_args(spec.args, spec.kwargs)
            value = fn(*args, **kwargs)
            if streaming:
                # Stream items while still "executing" (cancel interrupts
                # the iteration via the same SIGINT path).
                gen_count, gerr, gexc = self._stream_generator(
                    spec, value, conn)
                if gerr is not None:
                    error_blob = gerr
                    retryable = self._exception_retryable(spec, gexc)
        except BaseException as e:  # noqa: BLE001 — user code may raise anything
            timed_out = self._consume_timeout(spec, e)
            if timed_out is not None:
                error_blob, retryable = timed_out
            else:
                error_blob = self._make_error_blob(spec, e)
                retryable = self._exception_retryable(spec, e)
        finally:
            if timer is not None:
                timer.cancel()
            self._timed_out.discard((spec.task_id, spec.attempt))
            self._current_task_id = None
            watchdog.task_end(error_blob is None)
            _tracing.task_execute_end(trace_h, ok=error_blob is None)
            self._record_event(spec, t0, time.time(), error_blob is None)
            undo_env()
            for k, old in saved_env.items():
                if old is None:
                    os.environ.pop(k, None)
                else:
                    os.environ[k] = old
        try:
            results = (self._package_stream_completion(spec, gen_count, error_blob)
                       if streaming
                       else self._package_results(spec, value, error_blob))
        except KeyboardInterrupt:
            results = (self._package_stream_completion(spec, gen_count, error_blob)
                       if streaming
                       else self._package_results(spec, value, error_blob))
        except BaseException as e:
            error_blob = self._make_error_blob(spec, e)
            results = self._package_results(spec, None, error_blob)

        pusher = self._pusher_for(conn)
        # Compact `tasks_done` item (parsed by lease._task_done /
        # _ActorPipe._on_push): (task_id, attempt, results, error,
        # retryable, exec_failure).
        payload = (spec.task_id, spec.attempt, results, error_blob,  # rtcheck: wire=tasks_done.item
                   retryable, None)
        # Don't advertise transient (to-be-retried) errors: the owner will
        # resubmit, and a poisoned directory entry would outlive the retry.
        # Inline results aren't advertised at all: the owner resolves from
        # the direct reply, and a third-party borrower is served on demand
        # via the controller's need_object pull to the owner (reference:
        # owned inline objects live with the owner, not in the GCS).
        will_retry = (error_blob is not None and retryable
                      and spec.attempt < spec.max_retries)
        if not will_retry:
            for oid, inline, size, holder in results:
                if holder is not None:
                    self._advertise_pusher.add(self._advert_item(
                        oid, size, inline, holder, spec.owner_id,
                        error_blob))
        delivered = False
        for _ in range(2):  # a late cancel SIGINT must not lose the report
            try:
                if pusher is not None:
                    pusher.add(payload)
                    delivered = True
                break
            except KeyboardInterrupt:
                continue
        if will_retry or streaming:
            # The owner's requeue owns a retried outcome, and streaming
            # specs never ride the controller failover path (it has no item
            # transport): no dedup record for either.
            return
        # At-most-once across owner failover: make the final outcome
        # durable at the NODE. Holder already gone -> the owner can only
        # learn it through the failover re-dispatch, whose agent-side dedup
        # replays the record. Holder still connected -> park the payload
        # per connection; the prune republishes it only if the connection
        # dies with the reply possibly unflushed.
        import collections

        orphaned = None
        with self._ltask_lock:
            if delivered and not conn.closed:
                rq = self._recent_ltasks.get(conn)
                if rq is None:
                    rq = self._recent_ltasks[conn] = collections.deque(
                        maxlen=64)
                rq.append(payload)
            else:
                orphaned = [payload]
        if orphaned:
            self._report_orphaned(orphaned)

    def _execute_actor_task(self, spec: TaskSpec, conn=None) -> dict:
        error_blob = None
        value = None
        streaming = spec.num_returns == STREAMING
        gen_count = 0
        # Progress beacon for sync actor methods (threaded/default paths;
        # async methods ride the actor loop and are not thread-attributable).
        trace_h = _tracing.task_execute_begin(spec)
        watchdog.task_begin(spec.task_id, spec.name, spec.attempt,
                            spec.kind,
                            trace_id=spec.trace[0] if spec.trace else None)
        t0 = time.time()
        try:
            if self.actor_instance is None:
                raise RuntimeError("actor instance not initialized")
            ent = self._method_cache.get(spec.method_name)
            method = ent[0] if ent is not None and ent[0] is not None \
                else getattr(self.actor_instance, spec.method_name)
            if spec.args or spec.kwargs:
                args, kwargs = self.worker.decode_args(spec.args, spec.kwargs)
                value = method(*args, **kwargs)
            else:
                value = method()
            if streaming:
                gen_count, gerr, _ = self._stream_generator(spec, value, conn)
                if gerr is not None:
                    error_blob = gerr
        except BaseException as e:  # noqa: BLE001
            error_blob = self._make_error_blob(spec, e)
        watchdog.task_end(error_blob is None)
        _tracing.task_execute_end(trace_h, ok=error_blob is None)
        self._record_event(spec, t0, time.time(), error_blob is None)
        if streaming:
            return {"results": self._package_stream_completion(
                spec, gen_count, error_blob), "error": error_blob}
        return self._finish_actor_task(spec, value, error_blob)

    def _finish_actor_task(self, spec: TaskSpec, value, error_blob) -> dict:
        try:
            results = self._package_results(spec, value, error_blob)
        except BaseException as e:
            error_blob = self._make_error_blob(spec, e)
            results = self._package_results(spec, None, error_blob)

        # Advertise shm results to the controller (batched one-way frames)
        # so refs passed to third parties resolve; inline results live with
        # the owner (who gets them in the reply) and are served to borrowers
        # via the controller's need_object pull.
        for oid, inline, size, holder in results:
            if holder is not None:
                self._advertise_pusher.add(self._advert_item(
                    oid, size, inline, holder, spec.owner_id, error_blob))
        return {"results": results, "error": error_blob}


def _install_stack_dump():
    """SIGUSR1 -> dump all thread stacks to a per-pid file (the reporter
    role the reference fills with py-spy via the dashboard agent,
    dashboard/modules/reporter/). Read back by the node agent for the
    dashboard's /api/stacks endpoint.

    faulthandler.register installs a C-LEVEL handler on a pre-opened fd:
    it dumps even when the worker is hung inside native code holding the
    GIL — exactly the case an operator reaches for stacks. Dumps APPEND;
    the agent reads from its recorded offset once the file stops growing."""
    import faulthandler
    import signal

    from ray_tpu._private.rtconfig import stack_dump_path

    path = stack_dump_path(os.environ.get("RT_SESSION", ""), os.getpid())
    try:
        os.makedirs(os.path.dirname(path), exist_ok=True)
        f = open(path, "a")
        faulthandler.register(signal.SIGUSR1, file=f, all_threads=True)
    except Exception:
        # Registration failed (unwritable dir, ENOSPC): install a NO-OP
        # handler anyway — SIGUSR1's default disposition TERMINATES the
        # process, so a later /api/stacks probe must not kill a healthy
        # worker just because its dump file couldn't be opened.
        try:
            signal.signal(signal.SIGUSR1, lambda s_, f_: None)
        except Exception:
            pass


def main():
    import signal

    _prof = [None]

    def _term(signum, frame):
        if _prof[0] is not None:
            try:
                _prof[0].disable()
                _prof[0].dump_stats(os.path.join(
                    CONFIG.profile_worker, f"worker_{os.getpid()}.pstats"))
            except Exception:
                pass
        rpc.cleanup_sockets()
        os._exit(0)

    signal.signal(signal.SIGTERM, _term)
    _install_stack_dump()
    logging.basicConfig(level=logging.INFO, format=f"[worker %(process)d] %(message)s")
    proc = WorkerProc()
    proc.start()
    profile_dir = CONFIG.profile_worker
    if profile_dir:  # dev-only: per-worker cProfile dumps for hot-path work
        import cProfile

        pr = cProfile.Profile()
        _prof[0] = pr
        pr.enable()
        try:
            proc.run()
        except KeyboardInterrupt:
            pass
        finally:
            pr.disable()
            pr.dump_stats(os.path.join(profile_dir, f"worker_{os.getpid()}.pstats"))
        return
    try:
        proc.run()
    except KeyboardInterrupt:
        pass


if __name__ == "__main__":
    main()
