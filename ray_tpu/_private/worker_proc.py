"""Worker process entrypoint: executes tasks and hosts actors.

Parity target: the reference's task execution path — TaskReceiver
(core_worker/transport/task_receiver.h:51) + the Cython callback chain
(_raylet.pyx:2268 task_execution_handler ->
execute_task_with_cancellation_handler :2078): deserialize args, run the user
function, serialize/store returns (small inline, large to the shm store).
Actor calls arrive directly from callers on this process's RPC server
(reference direct actor transport) and execute in arrival order on the single
execution thread (reference sequential_actor_submit_queue.h).
"""

from __future__ import annotations

import asyncio
import logging
import os
import queue
import sys
import threading
import traceback

from ray_tpu._private import rpc
from ray_tpu._private.rtconfig import CONFIG
from ray_tpu._private.serialization import dumps_oob, serialize
from ray_tpu._private.task_spec import ACTOR_CREATE, ACTOR_TASK, NORMAL, TaskSpec
from ray_tpu._private.worker import ObjectRef, Worker, set_global_worker

logger = logging.getLogger(__name__)


class WorkerProc:
    def __init__(self):
        self.worker_id = os.environ["RT_WORKER_ID"]
        self.node_id = os.environ["RT_NODE_ID"]
        self.session = os.environ["RT_SESSION"]
        chost, cport = os.environ["RT_CONTROLLER"].rsplit(":", 1)
        ahost, aport = os.environ["RT_AGENT"].rsplit(":", 1)
        self.agent_addr = (ahost, int(aport))
        self.worker = Worker(
            mode="worker",
            session_id=self.session,
            controller_addr=(chost, int(cport)),
            node_id=self.node_id,
            agent_addr=self.agent_addr,
            worker_id=self.worker_id,
        )
        self.exec_queue: "queue.Queue" = queue.Queue()
        self.agent_conn: rpc.Connection | None = None
        self.actor_instance = None
        self.actor_id: str | None = None
        self._running = True

    # ------------------------------------------------------------ startup
    def start(self):
        self.worker.connect()
        set_global_worker(self.worker)
        self.worker.actor_call_handler = self._handle_actor_call

        async def _join_agent():
            self.agent_conn = await rpc.connect(
                *self.agent_addr,
                on_push=self._on_agent_push,
                on_close=lambda c: os._exit(0) if self._running else None,
            )
            await self.agent_conn.call(
                "register_worker", worker_id=self.worker_id, address=self.worker.server_addr
            )

        self.worker.io.run(_join_agent(), timeout=CONFIG.connect_timeout_s)

    async def _on_agent_push(self, conn, method, a):
        if method == "execute":
            self.exec_queue.put(("task", a["spec"], None))
        elif method == "exit":
            self._running = False
            self.exec_queue.put(("exit", None, None))

    async def _handle_actor_call(self, spec: TaskSpec):
        """Called on the IO thread for direct actor calls; bridges to the
        execution thread and awaits the reply."""
        loop = asyncio.get_running_loop()
        fut = loop.create_future()
        self.exec_queue.put(("actor_task", spec, (loop, fut)))
        return await fut

    # ---------------------------------------------------------- exec loop
    def run(self):
        while self._running:
            kind, spec, reply_slot = self.exec_queue.get()
            if kind == "exit":
                break
            try:
                if spec.kind == ACTOR_TASK:
                    reply = self._execute_actor_task(spec)
                    loop, fut = reply_slot
                    loop.call_soon_threadsafe(
                        lambda f=fut, r=reply: f.set_result(r) if not f.done() else None
                    )
                else:
                    self._execute_task(spec)
            except BaseException:
                traceback.print_exc()
        self.worker.disconnect()

    # ---------------------------------------------------------- execution
    def _package_results(self, spec: TaskSpec, value, error_blob):
        """Serialize return values: small inline, large into the node shm
        store with the agent as the advertised holder (it outlives workers)."""
        results = []
        oids = spec.return_object_ids()
        if error_blob is not None:
            for oid in oids:
                results.append((oid, None, 0, None))
            return results
        if spec.num_returns == 0:
            return results
        values = [value] if spec.num_returns == 1 else list(value)
        if spec.num_returns > 1 and len(values) != spec.num_returns:
            raise ValueError(
                f"task {spec.name} declared num_returns={spec.num_returns} "
                f"but returned {len(values)} values"
            )
        for oid, v in zip(oids, values):
            sobj = serialize(v, ref_class=ObjectRef)
            size = sobj.total_bytes()
            blob = sobj.to_bytes()
            if size <= CONFIG.max_inline_object_bytes:
                results.append((oid, [blob], size, None))
            else:
                self.worker.store.put(oid, [blob])
                results.append((oid, None, size, self.agent_addr))
        return results

    def _make_error_blob(self, spec: TaskSpec, e: BaseException):
        tb = traceback.format_exc()
        cause_header = None
        try:
            cause_header, cause_bufs = dumps_oob(e)
            if cause_bufs:
                cause_header = None  # keep error blobs simple: no oob bufs
        except Exception:
            cause_header = None
        h, bufs = dumps_oob(
            {
                "type": "TaskError",
                "function_name": spec.name,
                "traceback": tb,
                "cause": cause_header,
            }
        )
        return [h, *bufs]

    def _execute_task(self, spec: TaskSpec):
        error_blob = None
        value = None
        if spec.runtime_env.get("env_vars"):
            os.environ.update({k: str(v) for k, v in spec.runtime_env["env_vars"].items()})
        try:
            if spec.kind == ACTOR_CREATE:
                cls = self.worker.load_function(spec.function_id)
                args, kwargs = self.worker.decode_args(spec.args, spec.kwargs)
                self.actor_instance = cls(*args, **kwargs)
                self.actor_id = spec.actor_id
            else:
                fn = self.worker.load_function(spec.function_id)
                args, kwargs = self.worker.decode_args(spec.args, spec.kwargs)
                value = fn(*args, **kwargs)
        except BaseException as e:  # noqa: BLE001 — user code may raise anything
            error_blob = self._make_error_blob(spec, e)
            if spec.kind == ACTOR_CREATE:
                logger.error("actor __init__ failed:\n%s", traceback.format_exc())
        try:
            results = self._package_results(spec, value, error_blob)
        except BaseException as e:
            error_blob = self._make_error_blob(spec, e)
            results = self._package_results(spec, None, error_blob)

        async def _report():
            payload = dict(task_id=spec.task_id, results=results, error=error_blob, spec=None)
            if spec.kind == ACTOR_CREATE:
                payload["actor_address"] = self.worker.server_addr
            await self.worker.controller.push("task_done", **payload)
            if spec.kind == NORMAL:
                await self.agent_conn.push("worker_idle", worker_id=self.worker_id)

        self.worker.io.run(_report())

    def _execute_actor_task(self, spec: TaskSpec) -> dict:
        error_blob = None
        value = None
        try:
            if self.actor_instance is None:
                raise RuntimeError("actor instance not initialized")
            method = getattr(self.actor_instance, spec.method_name)
            args, kwargs = self.worker.decode_args(spec.args, spec.kwargs)
            value = method(*args, **kwargs)
        except BaseException as e:  # noqa: BLE001
            error_blob = self._make_error_blob(spec, e)
        try:
            results = self._package_results(spec, value, error_blob)
        except BaseException as e:
            error_blob = self._make_error_blob(spec, e)
            results = self._package_results(spec, None, error_blob)

        # Advertise results to the controller (async push) so refs passed on
        # to third parties resolve; the caller gets them in the reply already.
        async def _advertise():
            for oid, inline, size, holder in results:
                await self.worker.controller.push(
                    "register_put", oid=oid, size=size, inline=inline,
                    holder=holder, owner=spec.owner_id, error=error_blob)

        if results:
            self.worker.io.spawn(_advertise())
        return {"results": results, "error": error_blob}


def main():
    logging.basicConfig(level=logging.INFO, format=f"[worker %(process)d] %(message)s")
    proc = WorkerProc()
    proc.start()
    try:
        proc.run()
    except KeyboardInterrupt:
        pass


if __name__ == "__main__":
    main()
