"""Per-node agent: worker pool, task dispatch, object serving, heartbeats.

Parity target: the reference raylet (src/ray/raylet/raylet.h:33 +
node_manager.h:122): WorkerPool (worker_pool.h:228 — process prestart and
reuse), LocalTaskManager dispatch (local_task_manager.cc:124), object serving
(object_manager.h:106 Push/Pull), heartbeat/health (gcs_health_check_manager).
Scheduling decisions live in the controller (see controller.py); the agent
only executes dispatch orders — no local queueing/spillback.
"""

from __future__ import annotations

import asyncio
import logging
import os
import subprocess
import sys
import time
from collections import deque
from typing import Optional

from ray_tpu._private import events as events_mod
from ray_tpu._private import rpc, telemetry
from ray_tpu._private.ids import WorkerID
from ray_tpu._private.object_store import LocalStore
from ray_tpu._private.rtconfig import CONFIG
from ray_tpu._private.task_spec import ACTOR_CREATE, TaskSpec

logger = logging.getLogger(__name__)


class _WorkerSlot:
    __slots__ = ("worker_id", "proc", "conn", "state", "task_id", "actor_id", "address",
                 "registered", "dedicated", "idle_since", "assigned_at",
                 "held_resources", "device_pinned",
                 "beacon_task", "beacon_at", "beacon_silence",
                 "exit_emitted")

    def __init__(self, worker_id: str, proc, dedicated: bool = False):
        self.worker_id = worker_id
        self.proc = proc
        self.conn: Optional[rpc.Connection] = None
        self.state = "starting"  # starting | idle | reserved | busy | actor | dead
        self.task_id: Optional[str] = None
        self.actor_id: Optional[str] = None
        self.address = None
        self.registered = asyncio.Event()
        self.dedicated = dedicated  # spawned for an actor; never joins the pool
        self.idle_since: float = 0.0
        self.assigned_at: float = 0.0  # last task/lease/actor assignment time
        # Raw resources this slot's lease/task/actor holds — reported on
        # re-registration so a RESTARTED controller can rebuild accounting
        # (reference RayletNotifyGCSRestart reconciliation).
        self.held_resources: Optional[dict] = None
        # True while the worker reports live DeviceObjectTable pins: an
        # idle pool worker is still the STORAGE for those objects, so the
        # idle reaper must not kill it (README "Device objects").
        self.device_pinned = False
        # Stall-watchdog beacons (README "Stall detection & watchdogs"):
        # the executing task the worker last beaconed about, when, and its
        # self-reported progress silence. Beacons STOPPING while a task
        # runs trips the agent-side backstop (worker wedged in native code
        # can't run its own monitor thread).
        self.beacon_task: Optional[str] = None
        self.beacon_at: float = 0.0
        self.beacon_silence: float = 0.0
        # Event-plane dedup: exactly ONE worker_exit event per slot, no
        # matter which order the exit paths fire in (reap tick vs
        # _kill_slot vs idle reap vs OOM/stall report-then-kill).
        self.exit_emitted = False


class NodeAgent:
    def __init__(
        self,
        node_id: str,
        session_id: str,
        controller_addr: tuple,
        resources_raw: dict,
        labels: dict | None = None,
        host: str = "127.0.0.1",
        env: dict | None = None,
    ):
        self.node_id = node_id
        self.session_id = session_id
        self.controller_addr = controller_addr
        self.resources_raw = resources_raw
        self.labels = labels or {}
        self.host = host
        self.extra_env = env or {}
        self.server = rpc.RpcServer(self._on_request, self._on_push, self._on_worker_conn_close)
        self.store = LocalStore(session_id, CONFIG.object_store_memory_bytes, CONFIG.object_spill_dir, CONFIG.shm_dir)
        self.controller: Optional[rpc.Connection] = None
        self.workers: dict[str, _WorkerSlot] = {}
        self.jobs: dict[str, dict] = {}  # submission_id -> {proc, log_path, stopped}
        self._idle_waiters: deque = None  # set in start
        self._tasks: list[asyncio.Task] = []
        self._stopping = False
        self._reconnecting = False  # single-flight controller reconnect
        self.port = 0
        # Controller-minted at registration, echoed on every push so the
        # controller can fence messages from a previous life of this node.
        self.incarnation = 0
        # pid -> lock serializing stack-dump requests: two concurrent
        # /api/stacks probes share one append-mode dump file per pid, and
        # an unserialized second truncate would cut the first's read short.
        self._stack_locks: dict[int, asyncio.Lock] = {}
        # Telemetry plane (README "Telemetry & profiling"): sample batches
        # awaiting the next heartbeat (None while RT_TELEMETRY_INTERVAL_S
        # is unset — the heartbeat frame then stays byte-identical, pinned
        # by test) and the latest device-side series each worker pushed.
        self._telem_pending: deque | None = None
        self._worker_device_series: dict[str, dict] = {}
        self._node_cpu: telemetry.CpuTracker | None = None
        self._worker_cpu: telemetry.PidCpuTracker | None = None
        # Cluster event plane (README "Cluster events"): lifecycle events
        # this agent observed (worker start/exit with normalized cause,
        # dedup replays), awaiting the next heartbeat — or the next
        # worker_died push, which carries them so an exit event's seq lands
        # before the controller's restart/failover bookkeeping events.
        # None when the plane is off (RT_EVENTS_BUFFER=0): the heartbeat
        # frame stays byte-identical.
        self._pending_events: deque | None = (
            deque(maxlen=max(64, int(CONFIG.events_buffer)))
            if int(CONFIG.events_buffer) > 0 else None)
        # Direct-path task dedup (at-most-once across owner failover): a
        # leased worker whose owner connection severed reports the spec it
        # is still running (`ltask_running`) and its eventual outcome
        # (`ltask_done`). A controller re-dispatch of the same task id —
        # the owner failing the spec over — waits for the running entry to
        # resolve, then replies `dup` with the recorded results instead of
        # executing twice. task_id -> {"state", "worker_id", "results",
        # "error", "retryable", "event", "expires"}.
        self._direct_tasks: dict[str, dict] = {}

    async def start(self) -> int:
        self._idle_waiters = deque()
        self.port = await self.server.start(self.host, 0)
        # Initial connect retries like the reconnect path: a node joining
        # while the controller restarts (or before it finishes binding)
        # must not crash out on one refused connection.
        deadline = time.monotonic() + CONFIG.connect_timeout_s
        while True:
            try:
                self.controller = await rpc.connect(
                    *self.controller_addr,
                    on_request=self._on_ctrl_request,
                    on_push=self._on_ctrl_push,
                    on_close=self._on_ctrl_conn_close,
                    label="ctrl",
                )
                break
            except OSError:
                if time.monotonic() >= deadline:
                    raise
                await asyncio.sleep(0.5)
        rep = await self.controller.call(
            "register",
            kind="node",
            node_id=self.node_id,
            address=(self.host, self.port),
            resources=self.resources_raw,
            labels=self.labels,
        )
        self.incarnation = rep.get("incarnation") or 0
        CONFIG.load_snapshot(rep["config"])
        self.logs_enabled = bool(rep.get("log_sub", False))
        self._tasks.append(asyncio.ensure_future(self._heartbeat_loop()))
        self._tasks.append(asyncio.ensure_future(self._reap_loop()))
        if telemetry.interval_s() > 0:
            # Bounded: a controller outage must not grow an unbounded
            # sample backlog — oldest batches shed, ring discipline. Sized
            # so a full heartbeat interval of ticks fits with slack (a
            # fast sampler under a slow heartbeat must not shed in steady
            # state), never below the 16-batch outage floor.
            per_beat = CONFIG.heartbeat_interval_s / max(
                0.05, telemetry.interval_s())
            self._telem_pending = deque(maxlen=max(16, int(per_beat) + 8))
            self._node_cpu = telemetry.CpuTracker()
            self._worker_cpu = telemetry.PidCpuTracker()
            self._tasks.append(asyncio.ensure_future(self._telemetry_loop()))
        if CONFIG.memory_monitor_refresh_ms > 0:
            self._tasks.append(asyncio.ensure_future(self._memory_monitor_loop()))
        if CONFIG.prestart_workers and self.resources_raw.get("CPU", 0) > 0:
            self._spawn_worker()  # hide first-task process startup latency
        return self.port

    async def stop(self):
        self._stopping = True
        for t in self._tasks:
            t.cancel()
        for slot in list(self.workers.values()):
            self._kill_slot(slot, cause=events_mod.CAUSE_SHUTDOWN,
                            why="node agent shutdown")
        # Final best-effort heartbeat carrying the shutdown worker_exits:
        # the heartbeat loop is already cancelled, and undelivered events
        # here would leave every worker_start without its exit pair when
        # the controller outlives this agent.
        evs = self._drain_events()
        if evs and self.controller is not None and not self.controller.closed:
            try:
                await self.controller.push(
                    "heartbeat", node_id=self.node_id,
                    incarnation=self.incarnation,
                    shm_used=self.store.shm_dir_usage(), events=evs)
            except Exception:
                pass
        await self.server.stop()
        if self.controller is not None:
            await self.controller.close()
        self.store.shutdown()

    # -------------------------------------------------- controller channel
    def _on_ctrl_conn_close(self, conn):
        """The controller went away. Agents OUTLIVE a controller restart
        (reference: raylets tolerate a GCS restart and re-register via
        RayletNotifyGCSRestart, core_worker.proto:459): retry the same
        address, then re-register with the current worker inventory so the
        restarted controller can rebuild its accounting. Running work keeps
        running throughout — leases/actor pipes are direct connections."""
        if self._stopping:
            return
        asyncio.ensure_future(self._ctrl_reconnect())

    def _worker_inventory(self) -> list:
        out = []
        for slot in self.workers.values():
            if slot.proc.poll() is not None or slot.address is None:
                continue
            out.append({
                "worker_id": slot.worker_id,
                "address": tuple(slot.address),
                "state": slot.state,
                "task_id": slot.task_id,
                "actor_id": slot.actor_id,
                "dedicated": slot.dedicated,
                "resources": slot.held_resources,
            })
        return out

    async def _ctrl_reconnect(self):
        if self._reconnecting:
            return  # single-flight: abandoned conns' on_close must not fork
        self._reconnecting = True
        try:
            await self._ctrl_reconnect_inner()
        finally:
            self._reconnecting = False

    async def _ctrl_reconnect_inner(self):
        deadline = time.monotonic() + CONFIG.controller_reconnect_timeout_s
        logger.warning("agent %s: controller connection lost; retrying %s",
                       self.node_id[:8], self.controller_addr)
        while not self._stopping and time.monotonic() < deadline:
            conn = None
            try:
                conn = await rpc.connect(
                    *self.controller_addr,
                    on_request=self._on_ctrl_request,
                    on_push=self._on_ctrl_push,
                    on_close=self._on_ctrl_conn_close,
                    timeout=5,
                    label="ctrl",
                )
                rep = await conn.call(
                    "register", kind="node", node_id=self.node_id,
                    address=(self.host, self.port),
                    resources=self.resources_raw, labels=self.labels,
                    workers=self._worker_inventory(), _timeout=10)
                self.controller = conn
                self.incarnation = rep.get("incarnation") or 0
                CONFIG.load_snapshot(rep["config"])
                self.logs_enabled = bool(rep.get("log_sub", False))
                logger.info("agent %s: re-registered with restarted "
                            "controller", self.node_id[:8])
                return
            except Exception:
                if conn is not None and not conn.closed:
                    try:
                        await conn.close()
                    except Exception:
                        pass
                await asyncio.sleep(0.5)
        if self._stopping:
            return
        logger.error("agent %s: controller gone for %.0fs; shutting down",
                     self.node_id[:8], CONFIG.controller_reconnect_timeout_s)
        if os.environ.get("RT_AGENT_STANDALONE"):
            os._exit(1)

    async def _on_ctrl_request(self, conn, method, a):
        if method == "dispatch":
            return await self._dispatch(a["spec"])
        if method == "dispatch_batch":
            # One frame per scheduling pass per node; worker acquisition
            # fans out concurrently and each spec is reported EAGERLY via a
            # `dispatched` push the moment its acquisition resolves (frames
            # coalesce on the wire) — a warm pool hit must not wait for a
            # cold spawn sharing its batch. The call reply is the barrier:
            # it follows every push on this ordered connection.
            async def _one(spec):
                dup = await self._consume_direct_dup(spec.task_id,
                                                     spec.attempt)
                if dup is not None:
                    self._emit_event(
                        "lease_dedup_replay",
                        f"replayed recorded outcome for task "
                        f"{spec.task_id[:12]} a{spec.attempt} (failover "
                        f"re-dispatch absorbed; exactly-once)",
                        entity=(spec.task_id, dup.get("worker_id")),
                        attrs={"attempt": spec.attempt})
                    out = {"task_id": spec.task_id, "ok": True, "dup": True,
                           "worker_id": None, "results": dup.get("results"),
                           "error": dup.get("error"),
                           "retryable": dup.get("retryable", False)}
                    try:
                        await conn.push("dispatched", **out)
                    except Exception:
                        pass
                    return out
                try:
                    rep = await self._dispatch(spec)
                    out = {"task_id": spec.task_id, "ok": True,
                           "worker_id": rep["worker_id"]}
                except Exception as e:
                    out = {"task_id": spec.task_id, "ok": False,
                           "error": repr(e)}
                try:
                    await conn.push("dispatched", **out)
                except Exception:
                    pass  # conn died: the controller's barrier requeues
                return out

            results = await asyncio.gather(*[_one(s) for s in a["specs"]])
            return {"results": list(results)}
        if method in ("lease_worker", "lease_workers"):
            count = max(1, int(a.get("count", 1)))

            async def _lease_one():
                try:
                    slot = await self._acquire_pool_worker()
                except Exception:
                    return None
                if conn.closed:
                    # The controller died while we were acquiring: the reply
                    # can never be delivered, and marking the slot leased
                    # would orphan it FOREVER (no owner will ever return it)
                    # while its ghost acquisition starves real waiters after
                    # the controller restarts. Re-idle the slot.
                    self._worker_became_idle(slot)
                    return None
                slot.state = "leased"
                slot.assigned_at = time.monotonic()
                slot.held_resources = a.get("resources")
                return {"worker_id": slot.worker_id, "address": slot.address}

            # The whole batch acquires concurrently (slot reservation is
            # synchronous, so no double-grant) and partial fills are fine —
            # the controller releases what it placed but didn't get.
            out = [w for w in await asyncio.gather(
                *[_lease_one() for _ in range(count)]) if w is not None]
            if method == "lease_worker":  # single-grant wire compat
                if not out:
                    raise rpc.RpcError("no worker available for lease")
                return out[0]
            return {"workers": out}
        if method == "worker_stacks":
            return await self._worker_stacks(a["worker_id"])
        if method == "profile_worker":
            return await self._profile_worker(a)
        if method == "run_job":
            return self._run_job(a)
        if method == "stop_job":
            return self._stop_job(a["submission_id"])
        if method == "job_logs":
            return self._job_logs(a["submission_id"], int(a.get("offset", 0)))
        raise rpc.RpcError(f"agent: unknown ctrl method {method}")

    async def _worker_stacks(self, worker_id: str) -> dict:
        """Live thread stacks of one worker (the py-spy/reporter-agent
        role, dashboard/modules/reporter/): SIGUSR1 triggers the worker's
        faulthandler dump; the agent reads the per-pid file back."""
        import signal

        from ray_tpu._private.rtconfig import stack_dump_path

        wid = self._resolve_worker_id(worker_id)
        slot = self.workers.get(wid) if wid else None
        if slot is None or slot.proc.poll() is not None:
            return {"found": False, "stacks": ""}
        pid = slot.proc.pid
        path = stack_dump_path(self.session_id, pid)
        # Serialize per pid: concurrent probes share one append-mode dump
        # file, and a second request's truncate would cut the first's
        # read short mid-dump.
        lock = self._stack_locks.setdefault(pid, asyncio.Lock())
        async with lock:
            if len(self._stack_locks) > 64:  # prune locks of gone workers
                live = {s.proc.pid for s in self.workers.values()}
                for p in [p for p in self._stack_locks
                          if p not in live and p != pid]:
                    self._stack_locks.pop(p, None)
            # Truncate between requests: dumps append (C-level faulthandler
            # on an O_APPEND-style fd), and a polled endpoint would
            # otherwise grow the file unboundedly over a long-lived
            # worker's life.
            try:
                os.truncate(path, 0)
            except OSError:
                pass
            offset = 0
            try:
                os.kill(pid, signal.SIGUSR1)
            except OSError as e:
                return {"found": False, "stacks": f"signal failed: {e}"}
            # Dumps APPEND (C-level faulthandler on a pre-opened fd); wait
            # for growth past our offset, then for one quiet tick so a
            # mid-write read can't return a truncated dump.
            last = offset
            for _ in range(20):  # up to 1s
                await asyncio.sleep(0.05)
                try:
                    size = os.path.getsize(path)
                except OSError:
                    continue
                if size > offset and size == last:
                    # Read off the loop: the dump is usually small, but this
                    # loop also carries heartbeats and every worker's RPC —
                    # a slow /tmp (or a huge threaded-actor dump) must not
                    # stall them.
                    def _read_dump(path=path, offset=offset):
                        with open(path) as f:
                            f.seek(offset)
                            return f.read()

                    stacks = await asyncio.get_running_loop(
                        ).run_in_executor(None, _read_dump)
                    return {"found": True, "pid": pid, "stacks": stacks}
                last = size
            return {"found": False, "stacks": "worker did not dump in time"}

    # ------------------------------------------------- stall escalation
    async def _handle_stall_report(self, report: dict):
        """One escalation stage from a worker's watchdog (or the backstop
        below). warn: forward only. dump: capture the worker's live thread
        stacks through the SAME per-pid dump path /api/stacks uses (one
        implementation, one per-pid lock) and persist the whole report
        through the storage plane under <flight_dir>/. kill: all of that,
        then fell the worker — the death rides the ordinary worker_died /
        lease-failover machinery, so the stalled attempt retries instead of
        hanging its owner's get() forever."""
        stage = report.get("stage")
        wid = report.get("worker_id")
        slot = self.workers.get(wid) if wid else None
        if stage in ("dump", "kill"):
            try:
                stacks = await self._worker_stacks(wid)
                report["stacks"] = (stacks.get("stacks")
                                    if stacks.get("found") else None)
            except Exception:
                report["stacks"] = None
            await self._persist_flight_dump(report)
        try:
            await self.controller.push(
                "stall_report", report=report, node_id=self.node_id,
                incarnation=self.incarnation)
        except Exception:
            pass
        if stage == "kill" and slot is not None and slot.proc.poll() is None \
                and slot.state != "dead":
            # Re-validate against the worker's LATEST beacon before the
            # kill: the stack capture + flight dump above took real time,
            # and a task that finished right at the threshold may have
            # handed the worker to NEW work. Beacons keep naming the
            # stalest executing task, so a mismatch means the worker moved
            # on — killing it now would fail an innocent attempt.
            # (Backstop reports skip this: their whole premise is that
            # beacons stopped.)
            expected = report.get("task_id")
            if (not report.get("backstop") and expected is not None
                    and slot.beacon_task != expected):
                logger.info(
                    "stall kill aborted: worker %s no longer executing "
                    "task %s (moved on)", wid[:8], str(expected)[:12])
                return
            reason = (f"stalled: task {report.get('name')!r} made no "
                      f"progress for {report.get('silence_s')}s "
                      f"(watchdog kill escalation)")
            if report.get("trace_id"):
                # Traced task: name the trace so the failure message links
                # straight to `ray-tpu timeline --trace <id>`.
                reason += f" [trace {str(report['trace_id'])[:16]}]"
            logger.warning("stall watchdog: killing worker %s — %s",
                           wid[:8], reason)
            # Report BEFORE terminating (the OOM-kill pattern) so owners
            # see an attributed death, then kill; retries ride the
            # existing paths from here.
            await self._worker_exited(slot, reason, cause="stall")
            self._kill_slot(slot)

    async def _persist_flight_dump(self, report: dict):
        """Write the StallReport (flight-recorder ring + stacks included)
        through the PR 8 storage backend so it survives the process. Train
        runs route this under <run>/flight/ via RT_STALL_FLIGHT_DIR."""
        import json as _json

        try:
            from ray_tpu import storage

            flight_dir = report.get("flight_dir") or os.path.join(
                CONFIG.session_dir, self.session_id, "flight")
            name = (f"{int((report.get('time') or time.time()) * 1000)}"
                    f"_{report.get('pid')}_{report.get('stage')}.json")
            path = storage.join(flight_dir, name)
            blob = _json.dumps(report, default=str).encode()

            def _put():
                storage.makedirs(flight_dir)
                storage.put(path, blob)

            await asyncio.to_thread(_put)
            report["flight_path"] = path
        except Exception:
            logger.exception("stall watchdog: flight dump failed")

    def _beacon_ages(self) -> dict | None:
        """task_id -> seconds since the executing worker's last progress,
        shipped with heartbeats so `get(timeout=)` failures and
        `task_status` can name how long the producer has been silent."""
        now = time.monotonic()
        out = {}
        for slot in self.workers.values():
            if slot.beacon_task is not None and slot.beacon_at:
                out[slot.beacon_task] = round(
                    slot.beacon_silence + (now - slot.beacon_at), 3)
        return out or None

    # ------------------------------------------------------------- jobs
    # Reference: the job supervisor runs the entrypoint as a shell
    # subprocess with RAY_ADDRESS injected and streams its output to a
    # per-job log file (dashboard/modules/job/job_manager.py:60,
    # job_supervisor's _exec_entrypoint). Same shape here: the agent owns
    # the driver subprocess; the controller owns the status table.
    def _run_job(self, a: dict) -> dict:
        sid = a["submission_id"]
        env = dict(os.environ)
        env.update(self.extra_env)
        import ray_tpu

        pkg_root = os.path.dirname(os.path.dirname(os.path.abspath(ray_tpu.__file__)))
        env["PYTHONPATH"] = pkg_root + os.pathsep + env.get("PYTHONPATH", "")
        env["RT_ADDRESS"] = f"{self.controller_addr[0]}:{self.controller_addr[1]}"
        env["RT_JOB_SUBMISSION_ID"] = sid
        for k, v in ((a.get("runtime_env") or {}).get("env_vars") or {}).items():
            env[k] = str(v)
        log_dir = os.path.join(CONFIG.session_dir, self.session_id, "logs")
        os.makedirs(log_dir, exist_ok=True)
        log_path = os.path.join(log_dir, f"job-{sid}.log")
        log_f = open(log_path, "ab")
        cwd = (a.get("runtime_env") or {}).get("working_dir") or None
        try:
            proc = subprocess.Popen(
                a["entrypoint"], shell=True, env=env, cwd=cwd,
                stdout=log_f, stderr=subprocess.STDOUT,
                start_new_session=True)  # own pgid: stop_job kills the tree
        except Exception as e:
            return {"status": "failed", "message": f"spawn failed: {e!r}"}
        finally:
            log_f.close()  # the child holds its own inherited fd
        self.jobs[sid] = {"proc": proc, "log_path": log_path, "stopped": False}
        asyncio.ensure_future(self._watch_job(sid, proc))
        return {"status": "running", "pid": proc.pid, "log_path": log_path}

    async def _watch_job(self, sid: str, proc: subprocess.Popen):
        while proc.poll() is None:
            await asyncio.sleep(0.1)
        ent = self.jobs.get(sid)
        stopped = bool(ent and ent["stopped"])
        try:
            await self.controller.push(
                "job_done", submission_id=sid, returncode=proc.returncode,
                stopped=stopped, node_id=self.node_id,
                incarnation=self.incarnation)
        except Exception:
            pass

    def _stop_job(self, sid: str) -> dict:
        import signal

        ent = self.jobs.get(sid)
        if ent is None or ent["proc"].poll() is not None:
            return {"stopped": False}
        ent["stopped"] = True
        try:
            os.killpg(ent["proc"].pid, signal.SIGTERM)
        except Exception:
            ent["proc"].terminate()

        async def _escalate(proc=ent["proc"]):
            for _ in range(30):  # 3s grace, then SIGKILL the group
                if proc.poll() is not None:
                    return
                await asyncio.sleep(0.1)
            try:
                os.killpg(proc.pid, signal.SIGKILL)
            except Exception:
                proc.kill()

        asyncio.ensure_future(_escalate())
        return {"stopped": True}

    #: Per-call byte cap for job_logs replies (the PR 12 uniform truncation
    #: discipline): an unbounded tail-from-offset read would buffer a whole
    #: multi-GB log into ONE RPC reply frame. Callers loop while
    #: `truncated` is true (job_submission._read_logs_from).
    JOB_LOG_CHUNK_BYTES = 1 << 20

    def _job_logs(self, sid: str, offset: int) -> dict:
        ent = self.jobs.get(sid)
        if ent is None:
            return {"data": b"", "offset": offset, "found": False,
                    "truncated": False}
        try:
            with open(ent["log_path"], "rb") as f:
                f.seek(offset)
                data = f.read(self.JOB_LOG_CHUNK_BYTES)
                truncated = bool(f.read(1))  # more bytes remain past the cap
            return {"data": data, "offset": offset + len(data),
                    "found": True, "truncated": truncated}
        except OSError:
            return {"data": b"", "offset": offset, "found": False,
                    "truncated": False}

    async def _on_ctrl_push(self, conn, method, a):
        if method == "free":
            # Covers device-object EXPORT segments too; the pin itself is
            # unpinned by the controller's targeted device_free push on the
            # producer's own client connection.
            for oid in a["oids"]:
                self.store.purge(oid)
        elif method == "kill_worker":
            slot = self.workers.get(a["worker_id"])
            if slot is not None:
                self._kill_slot(slot)
        elif method == "unlease_worker":
            slot = self.workers.get(a["worker_id"])
            if slot is not None and slot.state == "leased":
                self._worker_became_idle(slot)
        elif method == "cancel_task":
            slot = self.workers.get(a["worker_id"])
            if slot is None or slot.task_id != a["task_id"]:
                return
            if a.get("force"):
                self._kill_slot(slot)
            elif slot.conn is not None and not slot.conn.closed:
                try:
                    await slot.conn.push("cancel", task_id=a["task_id"])
                except Exception:
                    pass
        elif method == "log_sub_state":
            self.logs_enabled = bool(a.get("on", False))
        elif method == "shutdown":
            await self.stop()

    # ------------------------------------------------------- event plane
    def _emit_event(self, kind: str, message: str = "", *,
                    severity: str | None = None, entity=(),
                    attrs: dict | None = None) -> None:
        """Queue one lifecycle event; it rides the next heartbeat (or the
        next worker_died push). No-op when the plane is off."""
        if self._pending_events is None:
            return
        self._pending_events.append(events_mod.build_event(
            kind, message, severity=severity, entity=entity,
            node_id=self.node_id, attrs=attrs,
            src=f"agent:{self.node_id[:12]}"))

    def _emit_worker_exit(self, slot: _WorkerSlot, cause: str, reason: str,
                          prev_state: str | None = None) -> None:
        """Exactly one worker_exit event per slot, whichever exit path
        observes it first (the slot-level flag dedups the report-then-kill
        shapes: OOM/stall `_worker_exited` + `_kill_slot`, idle reap's
        emit + kill)."""
        if slot.exit_emitted:
            return
        slot.exit_emitted = True
        self._emit_event(
            "worker_exit",
            f"worker {slot.worker_id[:12]} exited ({cause}): {reason}",
            severity=("info" if cause in (events_mod.CAUSE_SHUTDOWN,
                                          events_mod.CAUSE_IDLE_REAP)
                      else "warning"),
            entity=(slot.worker_id, slot.actor_id,
                    slot.task_id if prev_state == "busy" else None),
            attrs={"cause": cause, "state": prev_state or slot.state,
                   "pid": slot.proc.pid})

    def _drain_events(self) -> list | None:
        if not self._pending_events:
            return None
        return [self._pending_events.popleft()
                for _ in range(len(self._pending_events))]

    @staticmethod
    def _requeue_front(dq: deque | None, items: list | None) -> None:
        """Requeue drained-but-unsent batches BEHIND anything appended
        during the failed push (shed-oldest under a long outage). ONE
        discipline for every heartbeat-piggybacked plane — the shared
        rebuild lives in events.requeue_front; no lock here, the agent
        loop owns both deques."""
        events_mod.requeue_front(dq, items)

    def _requeue_events(self, evs: list) -> None:
        self._requeue_front(self._pending_events, evs)

    async def _heartbeat_loop(self):
        # ONE loop for the agent's lifetime: it reads self.controller every
        # beat, so it follows reconnects; failed pushes during an outage
        # are simply skipped (respawning a loop per reconnect would
        # accumulate duplicates).
        while True:
            await asyncio.sleep(CONFIG.heartbeat_interval_s)
            telem = None
            evs = None
            try:
                beat = dict(node_id=self.node_id,
                            incarnation=self.incarnation,
                            shm_used=self.store.shm_dir_usage())
                beacons = self._beacon_ages()
                if beacons:  # frame unchanged when the watchdog is idle
                    beat["beacons"] = beacons
                if self._telem_pending:
                    # Telemetry piggybacks on the heartbeat (no new
                    # connection or cadence — the PR 11 span-drain shape);
                    # with sampling off the frame is byte-identical.
                    telem = [self._telem_pending.popleft()
                             for _ in range(len(self._telem_pending))]
                    beat["telemetry"] = telem
                evs = self._drain_events()
                if evs:  # frame unchanged when no lifecycle event is queued
                    beat["events"] = evs
                await self.controller.push("heartbeat", **beat)
            except Exception:
                # Controller away: requeue both piggybacked planes for the
                # next beat (shed-oldest discipline — see _requeue_front).
                self._requeue_front(self._telem_pending, telem)
                self._requeue_front(self._pending_events, evs)
                continue

    # ----------------------------------------------------------- telemetry
    async def _telemetry_loop(self):
        """Per-node resource sampling (README "Telemetry & profiling"):
        node CPU/mem/disk + per-worker RSS/CPU% each tick, merged with the
        device-side series workers push (`worker_telemetry`). Batches park
        in a bounded ring until the next heartbeat carries them."""
        interval = max(0.05, telemetry.interval_s())
        while True:
            await asyncio.sleep(interval)
            try:
                self._telem_pending.append(self._sample_telemetry())
            except asyncio.CancelledError:
                raise
            except Exception:
                logger.debug("telemetry sample tick failed", exc_info=True)

    def _sample_telemetry(self) -> dict:
        """One sample batch (sync — /proc reads are microseconds; the same
        off-loop-call shape as _memory_usage_fraction)."""
        workers: dict[str, dict] = {}
        total_rss = 0
        running = 0
        live_pids = []
        for wid, slot in self.workers.items():
            if slot.proc.poll() is not None:
                continue
            pid = slot.proc.pid
            live_pids.append(pid)
            if slot.state in ("busy", "actor"):
                running += 1
            w: dict = {"cpu": self._worker_cpu.percent(pid)}
            rss = telemetry.pid_rss_bytes(pid)
            if rss is not None:
                w["rss"] = rss
                total_rss += rss
            dev = self._worker_device_series.get(wid)
            if dev:
                # Staleness bound: a worker whose sampler stopped pushing
                # (GIL-holding native call, failed pushes) must not have
                # its last hbm/compile values re-stamped as fresh forever.
                series, pushed = dev
                if time.monotonic() - pushed < 3.0 * max(
                        0.05, telemetry.interval_s()) + 1.0:
                    w.update(series)
                else:
                    self._worker_device_series.pop(wid, None)
            workers[wid] = w
        self._worker_cpu.prune(live_pids)
        node = {
            "cpu": self._node_cpu.percent(),
            "mem": telemetry.mem_percent(),
            "disk": telemetry.disk_percent(CONFIG.session_dir),
            "rss": total_rss,
            "tasks_running": running,
        }
        return {"ts": time.time(), "node": node, "workers": workers}

    async def _profile_worker(self, a: dict) -> dict:
        """On-demand profile capture of a live worker (reference: the
        reporter agent's py-spy endpoints). The worker runs the sampler
        in-process (its IO loop stays free while the exec thread works);
        the agent persists the rendered profile through the storage plane
        under <session>/profiles/ and returns the metadata row. A worker
        dying mid-capture is an attributed error, never a hang (the
        capture call is bounded and the conn close fails it fast)."""
        req = a.get("worker_id") or ""
        wid = self._resolve_worker_id(req)
        slot = self.workers.get(wid) if wid else None
        if slot is None or slot.proc.poll() is not None or slot.conn is None \
                or slot.conn.closed:
            nmatch = sum(1 for w in self.workers if w.startswith(req))
            if wid is None and nmatch > 1:
                return {"found": False,
                        "error": f"worker id prefix {req[:12]!r} is "
                                 f"ambiguous on node {self.node_id[:8]} "
                                 f"({nmatch} workers match) — use a "
                                 f"longer prefix"}
            return {"found": False,
                    "error": f"worker {req[:12]} not "
                             f"alive on node {self.node_id[:8]}"}
        seconds = telemetry.clamp_profile_seconds(a.get("seconds"))
        mode = a.get("mode") or "cpu"
        if mode not in ("cpu", "jax"):
            return {"found": False, "error": f"unknown profile mode {mode!r}"}
        try:
            rep = await slot.conn.call(
                "profile", mode=mode, seconds=seconds, hz=a.get("hz"),
                _timeout=seconds + 30.0)
        except Exception as e:
            return {"found": False,
                    "error": f"worker {wid[:12]} died or failed mid-capture "
                             f"({type(e).__name__}: {e}); profile aborted"}
        rep.update(worker_id=wid, node_id=self.node_id,
                   task_id=slot.task_id, actor_id=slot.actor_id,
                   created=time.time())
        try:
            meta = await asyncio.to_thread(self._persist_profile, wid, rep)
        except Exception as e:
            return {"found": False,
                    "error": f"profile captured but persist failed: {e!r}"}
        try:
            # Authoritative KV registration: a persist slower than the
            # controller's profile_worker timeout means the reply below is
            # dropped — this push still indexes the document so it never
            # orphans in the storage plane (controller dedups with the
            # reply-path registration).
            await self.controller.push("profile_persisted", profile=meta)
        except Exception:
            pass  # reply path registers; a lost push costs nothing
        return {"found": True, "profile": meta}

    def _resolve_worker_id(self, wid: str) -> str | None:
        """Exact worker id, or a unique prefix (CLI ergonomics — `ray-tpu
        top` prints 12-char prefixes)."""
        if wid in self.workers:
            return wid
        matches = [w for w in self.workers if w.startswith(wid)] if wid else []
        return matches[0] if len(matches) == 1 else None

    def _persist_profile(self, wid: str, rep: dict) -> dict:
        """Write the captured profile through the PR 8 storage backend
        (sync; runs in a thread). cpu -> one JSON doc (meta + collapsed
        stacks + Chrome-trace events); jax -> JSON meta + sibling .zip of
        the jax.profiler trace directory."""
        import json as _json

        from ray_tpu import storage

        pdir = telemetry.default_profile_dir(self.session_id)
        name = (f"{int((rep.get('created') or time.time()) * 1000)}"
                f"_{wid[:12]}_{rep.get('mode')}")
        storage.makedirs(pdir)
        doc = dict(rep)
        archive = doc.pop("archive", None)
        if archive is not None:
            apath = storage.join(pdir, name + ".zip")
            storage.put(apath, archive)
            doc["archive_path"] = apath
        path = storage.join(pdir, name + ".json")
        doc["name"] = name
        doc["path"] = path
        storage.put(path, _json.dumps(doc, default=str).encode())
        meta = {k: doc.get(k) for k in
                ("name", "path", "archive_path", "mode", "worker_id",
                 "node_id", "task_id", "actor_id", "pid", "seconds", "hz",
                 "samples", "files", "created")}
        meta["stacks"] = len(doc.get("collapsed") or {})
        return {k: v for k, v in meta.items() if v is not None}

    # ----------------------------------------------------- worker channel
    async def _on_request(self, conn, method, a):
        if method == "register_worker":
            slot = self.workers.get(a["worker_id"])
            if slot is None:
                raise rpc.RpcError("unknown worker")
            slot.conn = conn
            slot.address = tuple(a["address"])
            conn.label = conn.label or "worker"
            conn.meta["worker_id"] = a["worker_id"]
            slot.registered.set()
            if slot.dedicated:
                slot.state = "reserved"
            else:
                self._worker_became_idle(slot)
            return {"node_id": self.node_id, "config": CONFIG.snapshot()}
        if method == "fetch_object":
            mv = self.store.get(a["oid"])
            if mv is None:
                return {"found": False}
            off = a.get("offset")
            if off is None:
                return {"found": True, "data": mv, "size": len(mv)}
            return {"found": True, "size": len(mv),
                    "data": mv[off : off + a["length"]]}
        raise rpc.RpcError(f"agent: unknown method {method}")

    async def _on_push(self, conn, method, a):
        if method == "worker_idle":
            slot = self.workers.get(a["worker_id"])
            if slot is not None and slot.state == "busy":
                if slot.dedicated:
                    # One-shot worker (TPU task): the chip lease dies with it.
                    self._kill_slot(slot, cause=events_mod.CAUSE_SHUTDOWN,
                                    why="one-shot dedicated worker finished")
                else:
                    self._worker_became_idle(slot)
        elif method == "ltask_running":
            # A leased worker's owner connection severed mid-task: the spec
            # it is still executing is recorded so an owner-failover
            # re-dispatch of the same id parks instead of double-executing.
            rec = self._direct_tasks.get(a["task_id"])
            if rec is None:  # an already-arrived ltask_done wins
                self._direct_tasks[a["task_id"]] = {
                    "state": "running", "worker_id": a.get("worker_id"),
                    "attempt": a.get("attempt", 0),
                    "event": asyncio.Event(),
                    "expires": time.monotonic() + 600.0}
        elif method == "ltask_done":
            rec = self._direct_tasks.get(a["task_id"])
            if rec is None:
                rec = self._direct_tasks[a["task_id"]] = {
                    "event": asyncio.Event()}
            rec.update(state="done", worker_id=a.get("worker_id"),
                       attempt=a.get("attempt", 0),
                       results=a.get("results"), error=a.get("error"),
                       retryable=a.get("retryable", False),
                       expires=time.monotonic() + 600.0)
            rec["event"].set()
        elif method == "device_pins":
            slot = self.workers.get(a["worker_id"])
            if slot is not None:
                slot.device_pinned = bool(a.get("pinned"))
        elif method == "worker_telemetry":
            # Latest device-side series from a worker's sampler thread;
            # merged into the next node sample batch. Unknown worker ids
            # (a late push racing the exit path) are dropped.
            if a["worker_id"] in self.workers:
                self._worker_device_series[a["worker_id"]] = (
                    a["series"], time.monotonic())
        elif method == "watchdog_beacon":
            slot = self.workers.get(a["worker_id"])
            if slot is not None:
                slot.beacon_task = a.get("task_id")
                slot.beacon_at = time.monotonic()
                slot.beacon_silence = float(a.get("silence") or 0.0)
        elif method == "stall_report":
            asyncio.ensure_future(self._handle_stall_report(a["report"]))

    def _on_worker_conn_close(self, conn):
        wid = conn.meta.get("worker_id")
        if wid and wid in self.workers:
            asyncio.ensure_future(self._worker_exited(self.workers[wid], "connection lost"))

    # ---------------------------------------------------------- dispatch
    async def _consume_direct_dup(self, task_id: str, attempt: int = 0):
        """At-most-once guard for owner failover: if this (task id,
        attempt) already ran (or is still running) on a leased worker
        whose owner connection severed, return the recorded outcome
        instead of letting the dispatch execute it a second time. None =
        never seen here, execute normally. The attempt must match: a
        lineage-reconstruction resubmit of the same task id carries
        attempt+1 and MUST re-execute, not replay a stale record whose
        holders may point at the very object that was lost. A running
        record resolves on the worker's ltask_done or its death (death
        clears the record — the task never finished, so the re-dispatch
        may run); the wait is bounded so a lost ltask_done push cannot
        park the dispatch forever."""
        rec = self._direct_tasks.get(task_id)
        if rec is None or rec.get("attempt", 0) != attempt:
            return None
        if rec.get("state") == "running":
            try:
                await asyncio.wait_for(rec["event"].wait(), 600.0)
            except asyncio.TimeoutError:
                pass  # worker alive but outcome lost: fall through, execute
        rec = self._direct_tasks.pop(task_id, None)
        if rec is None or rec.get("state") != "done" \
                or rec.get("attempt", 0) != attempt:
            return None
        return rec

    def _purge_direct_tasks(self, worker_id: str):
        """The worker behind running dedup records died: the tasks never
        finished, so clear the records and unpark waiting dispatches."""
        for tid, rec in list(self._direct_tasks.items()):
            if rec.get("state") == "running" and rec.get("worker_id") == worker_id:
                self._direct_tasks.pop(tid, None)
                rec["event"].set()

    async def _dispatch(self, spec: TaskSpec) -> dict:
        slot = await self._acquire_worker(spec)
        slot.task_id = spec.task_id
        slot.assigned_at = time.monotonic()
        slot.held_resources = dict(spec.resources or {})
        if spec.kind == ACTOR_CREATE:
            slot.state = "actor"
            slot.actor_id = spec.actor_id
        else:
            slot.state = "busy"
        await slot.conn.push("execute", spec=spec)
        return {"worker_id": slot.worker_id}

    def _pool_cap(self) -> int:
        """Max concurrently running pool (non-actor) workers ~ CPU slots
        (reference WorkerPool keys by resource demand; we cap by node CPUs)."""
        cpu = self.resources_raw.get("CPU", 0) / CONFIG.resource_unit
        return max(1, int(cpu))

    @staticmethod
    def _needs_tpu(spec: TaskSpec) -> bool:
        return any(k.startswith("TPU") for k in (spec.resources or {}))

    async def _acquire_worker(self, spec: TaskSpec) -> _WorkerSlot:
        # Actors always get a dedicated fresh process (reference: dedicated
        # workers for actors, worker_pool.cc PopWorker for actor creation).
        # TPU-requesting tasks also get a dedicated worker: only those pay
        # the TPU-tunnel/jax plugin startup, and the chip lease dies with
        # the process (reference: GPU workers are not reused across owners).
        if spec.kind == ACTOR_CREATE or self._needs_tpu(spec):
            slot = self._spawn_worker(spec.runtime_env, dedicated=True,
                                      needs_tpu=self._needs_tpu(spec))
            await asyncio.wait_for(slot.registered.wait(), CONFIG.worker_register_timeout_s)
            return slot
        return await self._acquire_pool_worker()

    async def _acquire_pool_worker(self) -> _WorkerSlot:
        while True:
            for slot in self.workers.values():
                if slot.state == "idle":
                    slot.state = "reserved"
                    return slot
            pool_active = sum(
                1
                for s in self.workers.values()
                if not s.dedicated and s.state in ("starting", "reserved", "busy", "idle")
            )
            if pool_active < self._pool_cap():
                self._spawn_worker()
            fut = asyncio.get_running_loop().create_future()
            self._idle_waiters.append(fut)
            await asyncio.wait_for(fut, CONFIG.worker_register_timeout_s)

    def _worker_became_idle(self, slot: _WorkerSlot):
        slot.state = "idle"
        slot.task_id = None
        import time

        slot.idle_since = time.monotonic()
        while self._idle_waiters:
            fut = self._idle_waiters.popleft()
            if not fut.done():
                fut.set_result(None)
                break

    def _spawn_worker(self, runtime_env: dict | None = None, dedicated: bool = False,
                      needs_tpu: bool = False) -> _WorkerSlot:
        wid = WorkerID.from_random().hex()
        env = dict(os.environ)
        env.update(self.extra_env)
        if not needs_tpu and env.get("PALLAS_AXON_POOL_IPS"):
            # Don't pay the TPU-tunnel jax plugin registration (~2s of import
            # at every interpreter start) in workers that didn't ask for a
            # chip; they fall back to CPU jax if they use jax at all.
            env["PALLAS_AXON_POOL_IPS"] = ""
            env["JAX_PLATFORMS"] = "cpu"
        # Make sure spawned workers can import ray_tpu wherever the driver ran.
        import ray_tpu

        pkg_root = os.path.dirname(os.path.dirname(os.path.abspath(ray_tpu.__file__)))
        env["PYTHONPATH"] = pkg_root + os.pathsep + env.get("PYTHONPATH", "")
        env.update(
            RT_HOST=self.host,
            RT_WORKER_ID=wid,
            RT_NODE_ID=self.node_id,
            RT_SESSION=self.session_id,
            RT_CONTROLLER=f"{self.controller_addr[0]}:{self.controller_addr[1]}",
            RT_AGENT=f"{self.host}:{self.port}",
        )
        # Only dedicated (actor) workers bake the runtime env into the
        # process; pool workers apply+restore env per task instead, so a
        # reused worker can't leak a previous task's env (reference keys the
        # pool by runtime env, worker_pool.h:228).
        if runtime_env and dedicated:
            for k, v in (runtime_env.get("env_vars") or {}).items():
                env[k] = str(v)
        # Capture worker output and stream it to the driver via the
        # controller (reference log_monitor.py role): one reader thread per
        # worker into a bounded shared buffer, one timed flusher for all.
        proc = subprocess.Popen(
            [sys.executable, "-m", "ray_tpu._private.worker_proc"],
            env=env,
            stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT,
        )
        import threading

        self._ensure_log_flusher()
        threading.Thread(target=self._pump_worker_logs, args=(wid, proc),
                         daemon=True, name=f"logs-{wid[:6]}").start()
        slot = _WorkerSlot(wid, proc, dedicated=dedicated)
        self.workers[wid] = slot
        self._emit_event("worker_start",
                         f"worker {wid[:12]} spawned (pid {proc.pid})",
                         entity=(wid,),
                         attrs={"pid": proc.pid, "dedicated": dedicated})
        return slot

    MAX_LOG_BUF_LINES = 1000

    def _ensure_log_flusher(self):
        import threading

        if getattr(self, "_log_flusher", None) is None:
            self._log_bufs: dict = {}  # wid -> [pid, [lines]]
            self._log_lock = threading.Lock()
            self._log_flusher = threading.Thread(
                target=self._log_flush_loop, daemon=True, name="log-flush")
            self._log_flusher.start()

    def _pump_worker_logs(self, wid: str, proc):
        """Reader thread: drain the pipe (ALWAYS — a full pipe blocks the
        worker) into the bounded shared buffer; the flusher ships it."""
        try:
            for raw in iter(proc.stdout.readline, b""):
                line = raw.decode("utf-8", "replace").rstrip("\n")
                with self._log_lock:
                    ent = self._log_bufs.setdefault(wid, [proc.pid, []])
                    ent[1].append(line)
                    if len(ent[1]) > self.MAX_LOG_BUF_LINES:
                        del ent[1][: len(ent[1]) - self.MAX_LOG_BUF_LINES]
        except Exception:
            pass
        finally:
            try:
                proc.stdout.close()
            except Exception:
                pass

    def _log_flush_loop(self):
        """Timed flush (100ms): the last line of a burst must not wait for
        the NEXT line. Lines are dropped (bounded buffer) rather than
        shipped when no driver subscribed or the controller is away."""
        import time as _time

        while True:
            _time.sleep(0.1)
            with self._log_lock:
                batches, self._log_bufs = self._log_bufs, {}
            if not batches:
                continue
            if (not getattr(self, "logs_enabled", False)
                    or self.controller is None or self.controller.closed):
                continue  # nobody is listening: drop, don't accumulate
            for wid, (pid, lines) in batches.items():
                try:
                    self.controller.push_threadsafe(
                        "worker_logs", worker_id=wid, pid=pid,
                        node_id=self.node_id, lines=lines)
                except Exception:
                    pass

    def _kill_slot(self, slot: _WorkerSlot,
                   cause: str = events_mod.CAUSE_KILLED,
                   why: str = "explicit kill"):
        # Kills that no worker_died report precedes (ray_tpu.kill routed
        # via kill_worker, force-cancel, zombie reap) would otherwise leave
        # the causal chain without its worker_exit link — the dead-state
        # guards downstream skip the emission (the documented CAUSE_KILLED
        # would be unreachable). Report-then-kill paths (OOM/stall) already
        # emitted; the slot flag dedups.
        self._emit_worker_exit(slot, cause, why)
        slot.state = "dead"
        try:
            slot.proc.terminate()
        except Exception:
            pass
        # SIGTERM escalation: a worker wedged in native code (or whose main
        # thread can't reach the signal handler) survives terminate() — the
        # kill must not depend on the victim's cooperation (the reference
        # worker killer ends with SIGKILL for the same reason). The
        # callback also poll()s, so the child is reaped even if the reap
        # loop is momentarily behind.
        def _escalate(proc=slot.proc):
            try:
                if proc.poll() is None:
                    proc.kill()
                    proc.poll()
            except Exception:
                pass

        try:
            asyncio.get_running_loop().call_later(2.0, _escalate)
        except RuntimeError:
            _escalate()  # no loop (teardown path): escalate immediately

    async def _reap_loop(self):
        """Detect worker process exits (reference: raylet learns via socket
        disconnect + waitpid; we poll) and reap long-idle pool workers
        (reference worker_pool.cc TryKillingIdleWorkers,
        idle_worker_killing_time_threshold_ms), keeping one warm."""
        while True:
            await asyncio.sleep(0.2)
            try:
                await self._reap_tick()
            except asyncio.CancelledError:
                raise
            except Exception:
                # ONE bad tick (a report push racing a reconnecting
                # controller conn, a stall-report failure) must not fell
                # the loop for the agent's lifetime: with it dead, worker
                # exits go undetected and killed workers linger as
                # unreaped zombies whose pids stay probe-alive.
                logger.exception("agent reap tick failed; retrying")

    async def _reap_tick(self):
        for wid, slot in list(self.workers.items()):
            if slot.proc.poll() is not None and slot.state != "dead":
                await self._worker_exited(slot, f"exit code {slot.proc.returncode}")
        if self._direct_tasks:
            now = time.monotonic()
            for tid, rec in list(self._direct_tasks.items()):
                if rec.get("state") == "done" and rec["expires"] < now:
                    self._direct_tasks.pop(tid, None)
        # Stall backstop: a worker whose beacons STOPPED mid-task is too
        # wedged to run its own monitor thread (native code holding the
        # GIL) — its self-reported kill stage will never arrive, so the
        # agent synthesizes it once the beacon goes stale past the kill
        # threshold.
        kill_s = CONFIG.stall_kill_s
        if kill_s and kill_s > 0:
            interval = max(0.05, CONFIG.stall_beacon_interval_s)
            now = time.monotonic()
            for slot in list(self.workers.values()):
                # Beacons flow every tick from ANY armed worker, task or
                # no task — so the trigger is the beacon STREAM going
                # stale, not the task it names (a task that wedges in
                # native code before its first named beacon leaves
                # beacon_task None forever; the worker is just as dead).
                # beacon_at == 0 means the worker never armed a
                # watchdog (old build / just spawned): nothing to judge.
                if (not slot.beacon_at
                        or slot.state in ("dead", "starting")
                        or slot.proc.poll() is not None):
                    continue
                stale = now - slot.beacon_at
                if stale <= kill_s + 5 * interval:
                    continue
                report = {
                    "scope": "task", "stage": "kill", "backstop": True,
                    "task_id": slot.beacon_task or slot.task_id,
                    "name": None, "attempt": None, "kind": None,
                    "worker_id": slot.worker_id,
                    "node_id": self.node_id, "pid": slot.proc.pid,
                    "silence_s": round(slot.beacon_silence + stale, 3),
                    "time": time.time(),
                    "reason": (f"progress beacons stopped for "
                               f"{stale:.1f}s (watchdog starved — "
                               f"worker wedged in native code?)"),
                    "events": [], "flight_dir": None,
                }
                slot.beacon_at = 0.0  # escalate once
                slot.beacon_task = None
                await self._handle_stall_report(report)
        keep = CONFIG.idle_worker_keep_s
        if keep > 0:
            # Workers still pinning device objects are the storage for
            # those objects — exempt from the idle reap until the
            # owner-tracked frees drain their table.
            idle = [s for s in self.workers.values()
                    if s.state == "idle" and not s.dedicated
                    and not s.device_pinned]
            now = time.monotonic()
            warm = 1 if CONFIG.prestart_workers else 0
            for slot in sorted(idle, key=lambda s: s.idle_since)[: max(0, len(idle) - warm)]:
                if now - slot.idle_since > keep:
                    # Kill FIRST (atomic with the idle check — no await
                    # between them, so a lease/dispatch cannot claim the
                    # slot mid-reap), then report. The kill path skips
                    # the worker_died report (_worker_exited sees
                    # state=="dead"), but a pin could have landed since
                    # the last device_pins report: tell the controller
                    # so any device entries it produced go cleanly LOST
                    # instead of pointing at a dead address forever.
                    # Plane off => no pins possible, reap stays silent.
                    self._kill_slot(slot, cause=events_mod.CAUSE_IDLE_REAP,
                                    why=f"idle past {keep:.0f}s")
                    if CONFIG.device_objects:
                        # Pending events ride this push too (like
                        # _worker_exited's): the reap's worker_exit must
                        # get its seq BEFORE the device_objects_lost
                        # event this report's processing mints.
                        evs = self._drain_events()
                        kw = dict(worker_id=slot.worker_id,
                                  task_id=None, actor_id=None,
                                  reason="idle worker reaped",
                                  cause=events_mod.CAUSE_IDLE_REAP,
                                  node_id=self.node_id,
                                  incarnation=self.incarnation)
                        if evs:
                            kw["events"] = evs
                        try:
                            await self.controller.push("worker_died", **kw)
                        except Exception:
                            self._requeue_events(evs or [])

    async def _worker_exited(self, slot: _WorkerSlot, reason: str,
                             cause: str | None = None):
        if slot.state == "dead":
            # Reap the child BEFORE dropping the slot: this pop removes the
            # Popen from the reap loop's poll() sweep, and an unreaped
            # kill()ed worker lingers as a zombie whose pid still probes
            # alive (observed as a rare chaos-test flake — the zombie's
            # reaping then depended on GC/_cleanup luck). poll() here wins
            # almost always (the conn close that routes us here fires at
            # process exit); _kill_slot's escalation callback backstops the
            # not-yet-exited case.
            slot.proc.poll()
            self.workers.pop(slot.worker_id, None)
            self._purge_direct_tasks(slot.worker_id)
            self._worker_device_series.pop(slot.worker_id, None)
            return
        prev_state = slot.state
        slot.state = "dead"
        self.workers.pop(slot.worker_id, None)
        self._purge_direct_tasks(slot.worker_id)
        self._worker_device_series.pop(slot.worker_id, None)
        # ONE cause vocabulary for every exit path (README "Cluster
        # events"): the reap loop's raw exit codes, the OOM/stall kills,
        # and the idle reaper all collapse into events.EXIT_CAUSES, so the
        # worker_died report, the worker_exit event, and the owner-side
        # failure message all agree.
        cause = events_mod.normalize_exit_cause(cause, reason)
        self._emit_worker_exit(slot, cause, reason, prev_state)
        if prev_state in ("busy", "actor", "leased") or slot.actor_id:
            try:
                kw = dict(
                    worker_id=slot.worker_id,
                    task_id=slot.task_id if prev_state == "busy" else None,
                    actor_id=slot.actor_id,
                    reason=reason,
                    cause=cause,
                    node_id=self.node_id,
                    incarnation=self.incarnation,
                )
                # The pending events (incl. this exit's) ride the report
                # itself: the controller ingests them BEFORE minting its
                # restart/failover events, so causal chains stay ordered
                # under arrival-order seq minting.
                evs = self._drain_events()
                if evs:
                    kw["events"] = evs
                try:
                    await self.controller.push("worker_died", **kw)
                except Exception:
                    if evs:
                        self._requeue_events(evs)  # next heartbeat delivers
                    raise
            except Exception:
                pass

    # ------------------------------------------------------- OOM defense
    # Reference: memory_monitor.h (threshold poll over cgroup/meminfo) +
    # worker_killing_policy.h (prefer retriable, newest first). The agent
    # reports the kill BEFORE terminating the process so owners can surface
    # OutOfMemoryError instead of a generic crash.
    @staticmethod
    def _memory_usage_fraction() -> float:
        try:  # cgroup v2 (containers): respect the limit we actually have
            with open("/sys/fs/cgroup/memory.max") as f:
                lim = f.read().strip()
            if lim != "max":
                with open("/sys/fs/cgroup/memory.current") as f:
                    cur = int(f.read().strip())
                return cur / max(1, int(lim))
        except OSError:
            pass
        try:
            total = avail = None
            with open("/proc/meminfo") as f:
                for line in f:
                    if line.startswith("MemTotal:"):
                        total = int(line.split()[1])
                    elif line.startswith("MemAvailable:"):
                        avail = int(line.split()[1])
                    if total is not None and avail is not None:
                        return 1.0 - avail / max(1, total)
        except OSError:
            pass
        return 0.0

    def _pick_oom_victim(self) -> "_WorkerSlot | None":
        """Newest-first, retriable-first: pool task workers (tasks retry by
        default), then leased workers, then actors (restarts are opt-in)."""
        for states in (("busy",), ("leased",), ("actor",)):
            cands = [s for s in self.workers.values()
                     if s.state in states and s.proc.poll() is None]
            if cands:
                return max(cands, key=lambda s: s.assigned_at)
        return None

    async def _memory_monitor_loop(self):
        period = max(0.05, CONFIG.memory_monitor_refresh_ms / 1000.0)
        while True:
            await asyncio.sleep(period)
            threshold = CONFIG.memory_usage_threshold
            if threshold >= 1.0:
                continue
            frac = self._memory_usage_fraction()
            if frac < threshold:
                continue
            victim = self._pick_oom_victim()
            if victim is None:
                continue
            reason = (f"killed by the memory monitor: node memory usage "
                      f"{frac:.1%} exceeds threshold {threshold:.1%}")
            logger.warning("OOM defense: worker %s %s",
                           victim.worker_id[:8], reason)
            await self._worker_exited(victim, reason, cause="oom")
            self._kill_slot(victim)
            await asyncio.sleep(period)  # let the kill take effect


async def run_agent_until_cancelled(agent: NodeAgent):
    await agent.start()
    try:
        while True:
            await asyncio.sleep(3600)
    except asyncio.CancelledError:
        await agent.stop()


def main():
    """Standalone entry: `python -m ray_tpu._private.node_agent` (used by
    cluster_utils to start extra nodes, and by `ray-tpu start` CLI)."""
    import argparse
    import json
    import signal

    def _term(signum, frame):
        rpc.cleanup_sockets()
        os._exit(0)

    signal.signal(signal.SIGTERM, _term)

    p = argparse.ArgumentParser()
    p.add_argument("--controller", required=True)
    p.add_argument("--node-id", required=True)
    p.add_argument("--session", required=True)
    p.add_argument("--resources", required=True, help="json fixed-point raw map")
    p.add_argument("--labels", default="{}")
    args = p.parse_args()
    host, port = args.controller.rsplit(":", 1)
    os.environ["RT_AGENT_STANDALONE"] = "1"
    logging.basicConfig(level=logging.INFO)
    agent = NodeAgent(
        node_id=args.node_id,
        session_id=args.session,
        controller_addr=(host, int(port)),
        resources_raw=json.loads(args.resources),
        labels=json.loads(args.labels),
    )

    async def _run():
        await agent.start()
        while True:
            await asyncio.sleep(3600)

    try:
        asyncio.run(_run())
    except KeyboardInterrupt:
        pass


if __name__ == "__main__":
    main()
