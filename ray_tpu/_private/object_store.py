"""Shared-memory object store (plasma-equivalent) with disk spilling.

Parity target: reference object_manager/plasma/ (PlasmaStore store.h:55,
dlmalloc-on-shm, LRU EvictionPolicy, fallback-to-disk) and
raylet/local_object_manager.h:42 (spill/restore via external storage,
python/ray/_private/external_storage.py:72).

TPU-era design: instead of one store daemon with a dlmalloc heap, each object
is a file-backed mmap in /dev/shm named `rt_{session}_{oid}`. All processes on
a host share the namespace, so same-host reads attach the segment zero-copy
(numpy/jax arrays deserialize as views over the mapping via pickle5 oob
buffers). Cross-host reads go over the RPC object plane and materialize a
local secondary copy. Over-capacity stores spill LRU segments to disk and
restore on demand.
"""

from __future__ import annotations

import mmap
import os
import threading
import time


class LocalStore:
    def __init__(self, session_id: str, capacity_bytes: int, spill_dir: str, shm_dir: str = "/dev/shm"):
        self.session = session_id[:8]
        self.capacity = capacity_bytes
        self.spill_dir = os.path.join(spill_dir, self.session)
        self.shm_dir = shm_dir
        self._lock = threading.RLock()
        # oid -> {"size": int, "cap": int, "where": "shm"|"spill",
        #         "last_used": float, "mv": memoryview|None, "mm": mmap|None,
        #         "created": bool}
        # NOTE on reuse: freed segments must NOT be recycled for new objects.
        # The shm namespace is host-shared — a sibling process may have the
        # inode mapped (zero-copy reads), and deserialized arrays keep views
        # after local release, so rewriting a recycled segment would corrupt
        # live data. Safe recycling needs host-coordinated pinning (the
        # plasma client-release protocol) — the planned native store.
        self._objects: dict[str, dict] = {}
        self._used = 0

    # -- naming ------------------------------------------------------------
    def _path(self, oid: str) -> str:
        return os.path.join(self.shm_dir, f"rt_{self.session}_{oid}")

    def _spill_path(self, oid: str) -> str:
        return os.path.join(self.spill_dir, oid)

    # -- write -------------------------------------------------------------
    def put(self, oid: str, parts: list) -> int:
        """Write a flattened object blob (list of bytes-like) into shm.
        Returns total size. Idempotent per oid."""
        total = sum(p.nbytes if isinstance(p, memoryview) else len(p) for p in parts)
        with self._lock:
            if oid in self._objects:
                return self._objects[oid]["size"]
            self._maybe_evict(total)
            path = self._path(oid)
            fd = os.open(path, os.O_CREAT | os.O_RDWR | os.O_TRUNC, 0o600)
            try:
                os.ftruncate(fd, max(total, 1))
                mm = mmap.mmap(fd, max(total, 1))
            finally:
                os.close(fd)
            cap = max(total, 1)
            off = 0
            for p in parts:
                if not isinstance(p, (bytes, bytearray)):
                    p = memoryview(p).cast("B")  # write raw buffer, no copy
                mm[off : off + len(p)] = p
                off += len(p)
            self._objects[oid] = {
                "size": total,
                "cap": cap,
                "where": "shm",
                "last_used": time.monotonic(),
                "mm": mm,
                "mv": memoryview(mm)[:total],
                "created": True,
            }
            self._used += total
            return total


    # -- read --------------------------------------------------------------
    def get(self, oid: str):
        """Return a zero-copy memoryview of the blob, or None if absent.
        Attaches a segment created by another same-host process if needed;
        restores from spill if the segment was spilled."""
        with self._lock:
            ent = self._objects.get(oid)
            if ent is not None:
                ent["last_used"] = time.monotonic()
                if ent["where"] == "shm":
                    return ent["mv"]
                return self._restore(oid, ent)
            # try attach (created by a sibling process on this host)
            path = self._path(oid)
            try:
                fd = os.open(path, os.O_RDONLY)
            except FileNotFoundError:
                return None
            try:
                size = os.fstat(fd).st_size
                mm = mmap.mmap(fd, size, prot=mmap.PROT_READ)
            finally:
                os.close(fd)
            self._objects[oid] = {
                "size": size,
                "cap": size,
                "where": "shm",
                "last_used": time.monotonic(),
                "mm": mm,
                "mv": memoryview(mm),
                "created": False,
            }
            return self._objects[oid]["mv"]

    def contains(self, oid: str) -> bool:
        with self._lock:
            if oid in self._objects:
                return True
            return os.path.exists(self._path(oid))

    # -- spill/restore -----------------------------------------------------
    def _maybe_evict(self, incoming: int) -> None:
        if self._used + incoming <= self.capacity:
            return
        victims = sorted(
            (o for o, e in self._objects.items() if e["where"] == "shm" and e["created"]),
            key=lambda o: self._objects[o]["last_used"],
        )
        for oid in victims:
            if self._used + incoming <= self.capacity:
                break
            self._spill(oid)

    def _spill(self, oid: str) -> None:
        ent = self._objects[oid]
        os.makedirs(self.spill_dir, exist_ok=True)
        with open(self._spill_path(oid), "wb") as f:
            f.write(ent["mv"])
        self._release_mapping(ent)
        try:
            os.unlink(self._path(oid))
        except FileNotFoundError:
            pass
        ent["where"] = "spill"
        self._used -= ent["size"]

    def _restore(self, oid: str, ent: dict):
        self._maybe_evict(ent["size"])
        with open(self._spill_path(oid), "rb") as f:
            data = f.read()
        path = self._path(oid)
        fd = os.open(path, os.O_CREAT | os.O_RDWR | os.O_TRUNC, 0o600)
        try:
            os.ftruncate(fd, max(len(data), 1))
            mm = mmap.mmap(fd, max(len(data), 1))
        finally:
            os.close(fd)
        mm[: len(data)] = data
        ent.update(where="shm", mm=mm, mv=memoryview(mm)[: len(data)], created=True,
                   cap=max(len(data), 1))
        self._used += ent["size"]
        try:
            os.unlink(self._spill_path(oid))
        except FileNotFoundError:
            pass
        return ent["mv"]

    # -- delete ------------------------------------------------------------
    @staticmethod
    def _release_mapping(ent: dict) -> None:
        if ent.get("mv") is not None:
            try:
                ent["mv"].release()
            except BufferError:
                pass  # a deserialized array still views it; mmap stays alive
            ent["mv"] = None
        if ent.get("mm") is not None:
            try:
                ent["mm"].close()
            except BufferError:
                pass
            ent["mm"] = None

    def delete(self, oid: str) -> None:
        with self._lock:
            ent = self._objects.pop(oid, None)
            if ent is None:
                return
            if ent["where"] == "shm":
                if ent["created"]:
                    self._used -= ent["size"]
                    try:
                        os.unlink(self._path(oid))
                    except FileNotFoundError:
                        pass
            else:
                try:
                    os.unlink(self._spill_path(oid))
                except FileNotFoundError:
                    pass
            self._release_mapping(ent)

    def used_bytes(self) -> int:
        return self._used

    def num_objects(self) -> int:
        return len(self._objects)

    def shutdown(self) -> None:
        with self._lock:
            for oid in list(self._objects):
                self.delete(oid)
