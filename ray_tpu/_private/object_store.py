"""Shared-memory object store (plasma-equivalent) with disk spilling.

Parity target: reference object_manager/plasma/ (PlasmaStore store.h:55,
dlmalloc-on-shm, LRU EvictionPolicy, fallback-to-disk) and
raylet/local_object_manager.h:42 (spill/restore via external storage,
python/ray/_private/external_storage.py:72).

TPU-era design: instead of one store daemon with a dlmalloc heap, each object
is a file-backed mmap in /dev/shm named `rt_{session}_{oid}`. All processes on
a host share the namespace, so same-host reads attach the segment zero-copy
(numpy/jax arrays deserialize as views over the mapping via pickle5 oob
buffers). Cross-host reads go over the RPC object plane and materialize a
local secondary copy. Over-capacity stores spill LRU segments to disk and
restore on demand.
"""

from __future__ import annotations

import mmap
import os
import threading
import time


class _SpareLost(Exception):
    """A recycled spare segment vanished (session purge) between fill and
    rename; the caller re-runs the fill against a cold segment."""


class _StreamWriter:
    """Chunk sink for LocalStore.begin_stream (remote object fetch)."""

    __slots__ = ("_store", "oid", "_tmp", "_mm", "total", "_cap", "_done")

    def __init__(self, store: "LocalStore", oid: str, tmp: str, mm, total: int,
                 cap: int):
        self._store = store
        self.oid = oid
        self._tmp = tmp
        self._mm = mm
        self.total = total
        self._cap = cap
        self._done = False

    def write(self, offset: int, data) -> None:
        # Same copy machinery as put(): multi-MB fetch chunks use the
        # native threaded memcpy when available (the fetch pipeline calls
        # this off the event loop, overlapping the copy with socket recv).
        LocalStore._copy_in(self._mm, offset, data)

    def seal(self) -> bool:
        self._done = True
        return self._store._finish_stream(self.oid, self._tmp, self._mm,
                                          self.total, self._cap)

    def abort(self) -> None:
        if self._done:
            return
        self._done = True
        self._store._abort_stream(self._tmp, self._mm, self.total)


class LocalStore:
    def __init__(self, session_id: str, capacity_bytes: int, spill_dir: str, shm_dir: str = "/dev/shm"):
        self.session = session_id[:8]
        self.capacity = capacity_bytes
        self.spill_dir = os.path.join(spill_dir, self.session)
        self.shm_dir = shm_dir
        self._lock = threading.RLock()
        # oid -> {"size": int, "cap": int, "where": "shm"|"spill",
        #         "last_used": float, "mv": memoryview|None, "mm": mmap|None,
        #         "created": bool, "pin": str|None}
        self._objects: dict[str, dict] = {}
        self._used = 0
        # Warm-segment pool (the reference gets this from plasma's dlmalloc
        # arena: freed memory is re-handed to the next Create without giving
        # pages back to the kernel — cold tmpfs page faults cost ~4x warm
        # memcpy). Recycling a host-shared segment is only safe when no other
        # process can still read it, so readers hardlink a `.p{pid}` pin next
        # to the primary file before attaching; at free time the owner renames
        # the primary away (no new pins possible) and recycles only when
        # st_nlink shows no pins and the local memoryview releases cleanly.
        self._pool: list[dict] = []  # {"cap", "path", "mm"}
        self._pool_bytes = 0
        self._spare_seq = 0
        # Pins are named per (pid, store instance): two stores in one process
        # (driver + head agent share a process in local mode) must not share
        # a pin, or one store's clean delete would strip the other's guard.
        self._uid = f"{os.getpid()}x{id(self) & 0xFFFF:x}"
        self._pending_spare = None  # spare being filled by put_serialized

    # -- naming ------------------------------------------------------------
    def _path(self, oid: str) -> str:
        return os.path.join(self.shm_dir, f"rt_{self.session}_{oid}")

    def _spill_path(self, oid: str) -> str:
        return os.path.join(self.spill_dir, oid)

    # -- write -------------------------------------------------------------
    def _take_spare(self, total: int):
        """Best-fit warm segment with cap in [total, 4*total+1MB]."""
        best = None
        for i, sp in enumerate(self._pool):
            if total <= sp["cap"] <= 4 * total + (1 << 20):
                if best is None or sp["cap"] < self._pool[best]["cap"]:
                    best = i
        if best is None:
            return None
        sp = self._pool.pop(best)
        self._pool_bytes -= sp["cap"]
        return sp

    def _drop_spare(self, sp: dict):
        """Unlink+close a spare already removed (and deducted) from the pool."""
        try:
            os.unlink(sp["path"])
        except OSError:
            pass
        try:
            sp["mm"].close()
        except (BufferError, ValueError):
            pass

    @staticmethod
    def _copy_in(mm, off: int, p) -> int:
        """One part into the segment; multi-MB buffers use the native
        threaded memcpy (ray_tpu/_native) when available — on many-core TPU
        hosts a single-threaded copy leaves most of the memory bandwidth on
        the table (cf. reference plasma's threaded CreateAndSeal copies)."""
        if not isinstance(p, (bytes, bytearray)):
            p = memoryview(p).cast("B")  # write raw buffer, no copy
        n = len(p)
        if n >= (8 << 20) and (os.cpu_count() or 1) > 2:
            try:
                from ray_tpu import _native

                if _native.parallel_memcpy(memoryview(mm)[off:off + n], p):
                    return n
            except Exception:
                pass  # fall back to the plain slice copy
        mm[off : off + n] = p
        return n

    @staticmethod
    def _copy_buffers(mm, off: int, big_threshold: int, parts) -> int:
        """Copy `parts` into the mapping starting at `off`. Buffers at or
        above `big_threshold` take the native threaded memcpy directly (the
        per-part 8MB gate in _copy_in understates the win when one PUT
        carries many medium out-of-band buffers)."""
        native = None
        if big_threshold < (8 << 20) and (os.cpu_count() or 1) > 2:
            try:
                from ray_tpu import _native

                if _native.get_lib() is not None:
                    native = _native
            except Exception:
                native = None
        for p in parts:
            if not isinstance(p, (bytes, bytearray)):
                p = memoryview(p).cast("B")
            n = len(p)
            copied = False
            if native is not None and n >= big_threshold:
                try:
                    copied = bool(native.parallel_memcpy(
                        memoryview(mm)[off:off + n], p))
                except Exception:
                    copied = False
            if not copied:
                off += LocalStore._copy_in(mm, off, p)
            else:
                off += n
        return off

    def put_serialized(self, oid: str, sobj) -> int:
        """Serialize-into-shm put: lay a SerializedObject's wire format
        (see serialization.to_parts — single source of truth for the
        layout) directly into the destination mmap. The pickle-5
        out-of-band buffer views captured by serialize()'s buffer_callback
        are each written straight into the segment — no intermediate parts
        list, no joined blob, ONE pass over the payload bytes total — and
        a put carrying several medium buffers still gets the native
        threaded memcpy per buffer (put GB/s was at 0.587x of the memcpy
        ceiling with the old per-part 8MB gate). Returns total size."""
        import struct

        meta = sobj.to_parts_meta()
        total = len(meta) + len(sobj.header) + sum(
            8 + len(b) for b in sobj.buffers)
        with self._lock:
            ent = self._objects.get(oid)
            if ent is not None:
                return ent["size"]
            # Threaded copies pay off once the whole put is large: then
            # even ~1MB buffers ride the pool (faults + memcpy overlap).
            big = (8 << 20) if total < (8 << 20) else (1 << 20)
            while True:
                mm = self._make_segment(oid, total)
                off = self._copy_buffers(mm, 0, (8 << 20),
                                         (meta, sobj.header))
                for b in sobj.buffers:
                    off += LocalStore._copy_in(
                        mm, off, struct.pack("<Q", len(b)))
                    off = self._copy_buffers(mm, off, big, (b,))
                try:
                    self._commit_segment(oid, mm, total)
                    return total
                except _SpareLost:
                    continue  # purge raced the spare; rewrite cold

    def _make_segment(self, oid: str, total: int):
        """Allocate (or recycle) the backing mmap for a new object of
        `total` bytes — the shared front half of put()/put_serialized().
        Must be called under self._lock; returns the writable mmap."""
        path = self._path(oid)
        cap = max(total, 1)
        # Take a spare BEFORE evicting: reuse adds no net pages, so under
        # pressure the warm segment must not be the eviction victim.
        sp = self._take_spare(cap)
        self._maybe_evict(total)
        mm = None
        if sp is not None:
            try:
                # Grow the (possibly shrunk) spare back to this object's
                # size; data is written while it is still at the spare
                # name; _commit_segment renames it into place.
                if sp["cap"] != cap:
                    os.truncate(sp["path"], cap)
                mm = sp["mm"]
                self._pending_spare = sp
            except OSError:
                self._drop_spare(sp)
                sp = None
        if mm is None:
            self._pending_spare = None
            fd = os.open(path, os.O_CREAT | os.O_RDWR | os.O_TRUNC, 0o600)
            try:
                os.ftruncate(fd, cap)
                mm = mmap.mmap(fd, cap)
            finally:
                os.close(fd)
        return mm

    def _commit_segment(self, oid: str, mm, total: int):
        """Publish a segment filled by the caller (under self._lock):
        rename a recycled spare into place, register the entry. Returns the
        (possibly re-created) mapping."""
        path = self._path(oid)
        sp = getattr(self, "_pending_spare", None)
        self._pending_spare = None
        if sp is not None:
            try:
                os.rename(sp["path"], path)
            except OSError:
                # Lost the race with a session purge: the caller must
                # rewrite into a cold segment. Signalled via ValueError so
                # put_serialized stays rare-path simple.
                self._drop_spare(sp)
                raise _SpareLost()
        self._objects[oid] = {
            "size": total,
            "cap": max(total, 1),
            "where": "shm",
            "last_used": time.monotonic(),
            "mm": mm,
            "mv": memoryview(mm)[:total],
            "created": True,
            "pin": None,
        }
        self._used += total

    def put(self, oid: str, parts: list) -> int:
        """Write a flattened object blob (list of bytes-like) into shm.
        Returns total size. Idempotent per oid."""
        total = sum(p.nbytes if isinstance(p, memoryview) else len(p) for p in parts)
        with self._lock:
            if oid in self._objects:
                return self._objects[oid]["size"]
            path = self._path(oid)
            mm = None
            cap = max(total, 1)
            # Take a spare BEFORE evicting: reuse adds no net pages, so under
            # pressure the warm segment must not be the eviction victim.
            sp = self._take_spare(cap)
            self._maybe_evict(total)
            if sp is not None:
                try:
                    # Grow the (possibly shrunk) spare back to this object's
                    # size; write the data while it is still at the spare
                    # name, and only then rename — a sibling attach must
                    # never observe the previous object's bytes under the
                    # new oid (attachers probe /dev/shm with no lock).
                    if sp["cap"] != cap:
                        os.truncate(sp["path"], cap)
                    mm = sp["mm"]
                except OSError:
                    self._drop_spare(sp)
                    sp = None
            if mm is None:
                fd = os.open(path, os.O_CREAT | os.O_RDWR | os.O_TRUNC, 0o600)
                try:
                    os.ftruncate(fd, cap)
                    mm = mmap.mmap(fd, cap)
                finally:
                    os.close(fd)
            off = 0
            for p in parts:
                off += self._copy_in(mm, off, p)
            if sp is not None:
                try:
                    os.rename(sp["path"], path)
                except OSError:
                    # Lost the race with a session purge: fall back cold.
                    self._drop_spare(sp)
                    fd = os.open(path, os.O_CREAT | os.O_RDWR | os.O_TRUNC, 0o600)
                    try:
                        os.ftruncate(fd, cap)
                        mm = mmap.mmap(fd, cap)
                    finally:
                        os.close(fd)
                    off = 0
                    for p in parts:
                        off += self._copy_in(mm, off, p)
            self._objects[oid] = {
                "size": total,
                "cap": cap,
                "where": "shm",
                "last_used": time.monotonic(),
                "mm": mm,
                "mv": memoryview(mm)[:total],
                "created": True,
                "pin": None,
            }
            self._used += total
            return total

    def begin_stream(self, oid: str, total: int):
        """Start writing an object of known size that arrives in chunks
        (remote fetch): bytes land in a uniquely-named temp segment that is
        renamed into place at seal, so same-host attachers can never observe
        a half-written object. Returns None if the oid is already local."""
        with self._lock:
            if oid in self._objects:
                return None
            self._maybe_evict(total)
            # Reserve NOW: concurrent streams/puts must see these bytes as
            # committed or they over-commit the store during the transfer.
            self._used += total
            self._spare_seq += 1
            seq = self._spare_seq
        tmp = os.path.join(self.shm_dir,
                           f"rt_{self.session}_in{os.getpid()}_{seq}")
        cap = max(total, 1)
        try:
            fd = os.open(tmp, os.O_CREAT | os.O_RDWR | os.O_TRUNC, 0o600)
            try:
                os.ftruncate(fd, cap)
                mm = mmap.mmap(fd, cap)
            finally:
                os.close(fd)
        except OSError:
            with self._lock:
                self._used -= total
            raise
        return _StreamWriter(self, oid, tmp, mm, total, cap)

    def _finish_stream(self, oid: str, tmp: str, mm, total: int, cap: int) -> bool:
        """Seal a streamed segment (commits the reservation taken by
        begin_stream). Returns False if another copy won the race or the
        rename failed; the temp and the reservation are dropped."""
        def _drop():
            self._used -= total
            try:
                os.unlink(tmp)
            except OSError:
                pass
            try:
                mm.close()
            except (BufferError, ValueError):
                pass

        with self._lock:
            if oid in self._objects:
                _drop()
                return False
            try:
                os.rename(tmp, self._path(oid))
            except OSError:
                _drop()
                return False
            self._objects[oid] = {
                "size": total, "cap": cap, "where": "shm",
                "last_used": time.monotonic(), "mm": mm,
                "mv": memoryview(mm)[:total], "created": True, "pin": None,
            }
            return True

    def _abort_stream(self, tmp: str, mm, total: int) -> None:
        with self._lock:
            self._used -= total
        try:
            os.unlink(tmp)
        except OSError:
            pass
        try:
            mm.close()
        except (BufferError, ValueError):
            pass

    def detach(self, oid: str) -> None:
        """Drop our mapping but leave the file for other readers (used by
        executing workers after storing task results: the agent is the
        advertised holder, so keeping the producer's mapping alive would pin
        freed pages until the worker exits)."""
        with self._lock:
            ent = self._objects.pop(oid, None)
            if ent is None or ent["where"] != "shm":
                return
            if ent["created"]:
                self._used -= ent["size"]
            self._release_mapping(ent)


    # -- read --------------------------------------------------------------
    def get(self, oid: str):
        """Return a zero-copy memoryview of the blob, or None if absent.
        Attaches a segment created by another same-host process if needed;
        restores from spill if the segment was spilled."""
        with self._lock:
            ent = self._objects.get(oid)
            if ent is not None:
                ent["last_used"] = time.monotonic()
                if ent["where"] == "shm":
                    return ent["mv"]
                return self._restore(oid, ent)
            # Attach a segment created by a sibling process on this host.
            # The pin hardlink (created BEFORE opening) tells the creator's
            # free path that this segment must not be recycled; link() on a
            # path the owner already renamed away fails -> no stale attach.
            path = self._path(oid)
            if not os.path.exists(path):
                # Cheap miss: probing absent objects (every get() racing its
                # producer) must cost one stat, not a failed link() — link
                # is several times pricier on some kernels/sandboxes.
                return None
            pin = f"{path}.p{self._uid}"
            try:
                os.link(path, pin)
            except FileExistsError:
                # Stale pin from an earlier attach by this store (possibly
                # referencing a pre-spill inode): re-link so the pin is
                # guaranteed to name the CURRENT primary inode.
                try:
                    os.unlink(pin)
                    os.link(path, pin)
                except OSError:
                    return None
            except OSError:
                return None
            try:
                fd = os.open(path, os.O_RDONLY)
            except FileNotFoundError:
                try:
                    os.unlink(pin)
                except OSError:
                    pass
                return None
            try:
                size = os.fstat(fd).st_size
                mm = mmap.mmap(fd, size, prot=mmap.PROT_READ)
            finally:
                os.close(fd)
            self._objects[oid] = {
                "size": size,
                "cap": size,
                "where": "shm",
                "last_used": time.monotonic(),
                "mm": mm,
                "mv": memoryview(mm),
                "created": False,
                "pin": pin,
            }
            return self._objects[oid]["mv"]

    def contains(self, oid: str) -> bool:
        with self._lock:
            if oid in self._objects:
                return True
            return os.path.exists(self._path(oid))

    # -- spill/restore -----------------------------------------------------
    def _maybe_evict(self, incoming: int) -> None:
        if self._used + self._pool_bytes + incoming <= self.capacity:
            return
        # Spares are instantly reclaimable: drain the pool before spilling.
        while self._pool and self._used + self._pool_bytes + incoming > self.capacity:
            sp = self._pool.pop(0)
            self._pool_bytes -= sp["cap"]
            self._drop_spare(sp)
        if self._used + incoming <= self.capacity:
            return
        victims = sorted(
            (o for o, e in self._objects.items() if e["where"] == "shm" and e["created"]),
            key=lambda o: self._objects[o]["last_used"],
        )
        for oid in victims:
            if self._used + incoming <= self.capacity:
                break
            self._spill(oid)

    def _spill(self, oid: str) -> None:
        ent = self._objects[oid]
        os.makedirs(self.spill_dir, exist_ok=True)
        with open(self._spill_path(oid), "wb") as f:
            f.write(ent["mv"])
        self._release_mapping(ent)
        try:
            os.unlink(self._path(oid))
        except FileNotFoundError:
            pass
        ent["where"] = "spill"
        self._used -= ent["size"]

    def _restore(self, oid: str, ent: dict):
        self._maybe_evict(ent["size"])
        with open(self._spill_path(oid), "rb") as f:
            data = f.read()
        path = self._path(oid)
        fd = os.open(path, os.O_CREAT | os.O_RDWR | os.O_TRUNC, 0o600)
        try:
            os.ftruncate(fd, max(len(data), 1))
            mm = mmap.mmap(fd, max(len(data), 1))
        finally:
            os.close(fd)
        mm[: len(data)] = data
        ent.update(where="shm", mm=mm, mv=memoryview(mm)[: len(data)], created=True,
                   cap=max(len(data), 1))
        self._used += ent["size"]
        try:
            os.unlink(self._spill_path(oid))
        except FileNotFoundError:
            pass
        return ent["mv"]

    # -- delete ------------------------------------------------------------
    @staticmethod
    def _release_mapping(ent: dict) -> bool:
        """Release the local view+mapping; True if fully released (no live
        deserialized views)."""
        clean = True
        if ent.get("mv") is not None:
            try:
                ent["mv"].release()
                ent["mv"] = None
            except BufferError:
                clean = False  # a deserialized array still views it
        if clean and ent.get("mm") is not None:
            try:
                ent["mm"].close()
                ent["mm"] = None
            except BufferError:
                clean = False
        return clean

    def _unlink_pins(self, oid: str) -> None:
        # scandir + startswith instead of glob: glob compiles a regex per
        # call, and this runs on every purge.
        prefix = os.path.basename(self._path(oid)) + ".p"
        try:
            with os.scandir(self.shm_dir) as it:
                victims = [e.path for e in it if e.name.startswith(prefix)]
        except OSError:
            return
        for p in victims:
            try:
                os.unlink(p)
            except OSError:
                pass

    def delete(self, oid: str) -> None:
        with self._lock:
            ent = self._objects.pop(oid, None)
            if ent is None:
                return
            if ent["where"] != "shm":
                try:
                    os.unlink(self._spill_path(oid))
                except FileNotFoundError:
                    pass
                self._release_mapping(ent)
                return
            if not ent["created"]:
                # Attached copy: drop our pin only once no local views remain
                # (a live pin keeps the creator from recycling under us).
                if self._release_mapping(ent) and ent.get("pin"):
                    try:
                        os.unlink(ent["pin"])
                    except OSError:
                        pass
                return
            self._used -= ent["size"]
            path = self._path(oid)
            # Recycle: possible only if no local views remain. Rename the
            # primary away first (atomically stops new pins), then st_nlink
            # == 1 proves no reader ever pinned it.
            mv_clean = True
            if ent.get("mv") is not None:
                try:
                    ent["mv"].release()
                    ent["mv"] = None
                except BufferError:
                    mv_clean = False
            if mv_clean and ent.get("mm") is not None and len(self._pool) < 32 \
                    and self._pool_bytes + ent["cap"] <= self.capacity // 2:
                self._spare_seq += 1
                spare = os.path.join(
                    self.shm_dir, f"rt_{self.session}_sp{os.getpid()}_{self._spare_seq}")
                try:
                    os.rename(path, spare)
                except OSError:
                    self._release_mapping(ent)  # purged by another process
                    return
                try:
                    pinned = os.stat(spare).st_nlink != 1
                except OSError:
                    pinned = True
                if not pinned:
                    self._pool.append({"cap": ent["cap"], "path": spare, "mm": ent["mm"]})
                    self._pool_bytes += ent["cap"]
                    return
                try:
                    os.unlink(spare)
                except OSError:
                    pass
                self._unlink_pins(oid)
                self._release_mapping(ent)
                return
            # Not recyclable: free the names; pinned/viewing readers keep the
            # inode alive through their own mappings.
            try:
                os.unlink(path)
            except FileNotFoundError:
                pass
            self._unlink_pins(oid)
            self._release_mapping(ent)

    def used_bytes(self) -> int:
        return self._used

    def shm_dir_usage(self) -> int:
        """Ground-truth bytes of this session's segments in the shm dir —
        unlike _used, counts worker-produced segments their creator already
        detached (the node agent reports this in heartbeats for the
        cluster's backpressure accounting)."""
        prefix = f"rt_{self.session}_"
        total = 0
        try:
            with os.scandir(self.shm_dir) as it:
                for e in it:
                    if e.name.startswith(prefix):
                        try:
                            total += e.stat().st_size
                        except OSError:
                            pass
        except OSError:
            pass
        return total

    def num_objects(self) -> int:
        return len(self._objects)

    def purge(self, oid: str) -> None:
        """Remove an object's file names (primary + reader pins) whether or
        not this store holds an entry — used by the node agent on `free`
        pushes for segments created by its (possibly exited) workers."""
        with self._lock:
            if oid in self._objects:
                # delete() on an attached entry (created=False — the normal
                # agent state after serving fetch_object for a worker-produced
                # result) only drops our pin; the producing worker has already
                # detach()ed, so nobody else will ever unlink the primary.
                # Fall through and remove the names ourselves. Safe for
                # created entries too: the recycle path renames the primary
                # away before pooling it, so this unlink is a no-op there.
                self.delete(oid)
            try:
                os.unlink(self._path(oid))
            except OSError:
                pass
            self._unlink_pins(oid)

    def shutdown(self) -> None:
        with self._lock:
            for oid, ent in list(self._objects.items()):
                if ent.get("pin"):
                    try:
                        os.unlink(ent["pin"])  # process exiting; views moot
                    except OSError:
                        pass
                self.delete(oid)
            while self._pool:
                sp = self._pool.pop()
                self._pool_bytes -= sp["cap"]
                self._drop_spare(sp)
