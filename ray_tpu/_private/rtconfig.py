"""Global runtime flag table, env-overridable.

Parity target: reference src/ray/common/ray_config_def.h (224 RAY_CONFIG
entries, overridden by RAY_<name> env vars or ray.init(_system_config=...)).
Here: a typed registry; each flag is overridable via env var `RT_<NAME>` or
`init(_system_config={...})`.
"""

from __future__ import annotations

import json
import os
from typing import Any, Callable

_REGISTRY: dict[str, tuple[type, Any]] = {}


def _flag(name: str, typ: type, default: Any) -> None:
    _REGISTRY[name] = (typ, default)


# --- core timings / limits -------------------------------------------------
_flag("heartbeat_interval_s", float, 0.5)
# Node dead after N missed beats. 20 (=10s) rather than a twitchy few
# seconds: an agent spawning a burst of worker processes on a loaded host
# can starve its event loop for several seconds, and declaring it dead
# kills every actor it hosts (reference health checks tolerate ~30s:
# health_check_timeout_ms + failure_threshold). TCP disconnects still
# detect true death instantly via the connection-close path.
_flag("num_heartbeats_timeout", int, 20)
_flag("task_retry_delay_s", float, 0.05)
_flag("default_max_task_retries", int, 3)
_flag("default_max_actor_restarts", int, 0)
_flag("worker_register_timeout_s", float, 30.0)
_flag("connect_timeout_s", float, 30.0)
_flag("rpc_max_frame_bytes", int, 1 << 31)
# Objects smaller than this are passed inline in RPC messages instead of the
# shared-memory store (cf. reference max_direct_call_object_size, 100KB).
_flag("max_inline_object_bytes", int, 100 * 1024)
# Per-node shared-memory store capacity before spilling to disk.
_flag("object_store_memory_bytes", int, 2 * 1024 * 1024 * 1024)
_flag("object_spill_dir", str, "/tmp/ray_tpu/spill")
# Controller state snapshots (KV, named actors, PG defs) for
# restart-survival; empty = disabled (reference redis_store_client.h role).
_flag("controller_persist_dir", str, "")
_flag("shm_dir", str, "/dev/shm")
_flag("session_dir", str, "/tmp/ray_tpu")
_flag("min_workers_per_node", int, 0)
_flag("prestart_workers", bool, True)
_flag("idle_worker_keep_s", float, 300.0)
_flag("scheduler_spread_threshold", float, 0.5)  # hybrid policy pack->spread knob
_flag("lineage_reconstruction_enabled", bool, True)
# Controller-restart FT (reference RayletNotifyGCSRestart,
# core_worker.proto:459): agents/workers/drivers retry the controller
# address this long before giving up (workers exit; drivers error).
_flag("controller_reconnect_timeout_s", float, 30.0)
# Node-liveness suspicion window (reference GCS: a raylet connection drop
# does NOT immediately declare the node dead — health checks tolerate a
# reconnect). When the controller<->agent connection closes, the node goes
# SUSPECT for this long: leases and ALIVE actors are frozen, not restarted.
# An agent re-registering within the window reconciles in place; only
# expiry (or an explicit kill) runs the death path. <= 0 restores the old
# kill-on-close behavior.
_flag("node_suspect_grace_s", float, 2.0)
# Deterministic RPC fault injection (tests): enables rpc.FaultInjector so
# chaos tests can sever/drop/delay/duplicate frames on named connection
# classes. Zero-cost on the frame path when off.
_flag("fault_injection", bool, False)
# Borrower protocol: how long an owner-freed ESCAPED object survives at the
# controller waiting for a borrower to register (covers the in-flight window
# between the owner shipping a ref inside a payload and the receiving process
# materializing it; cf. reference reference_count.h borrower handshake).
_flag("borrowed_free_grace_s", float, 60.0)
# OOM defense (reference memory_monitor.h + worker_killing_policy.h): when
# node memory usage crosses the threshold, the agent kills the newest
# retriable worker. refresh_ms <= 0 disables the monitor.
_flag("memory_usage_threshold", float, 0.95)
_flag("memory_monitor_refresh_ms", int, 250)
# Object transfer: chunk size for remote fetches and the cap on bytes in
# flight across concurrent pulls (reference object_manager chunked transfer
# + pull_manager admission control).
_flag("object_chunk_bytes", int, 16 * 1024 * 1024)
_flag("pull_max_inflight_bytes", int, 512 * 1024 * 1024)
_flag("max_pending_calls_default", int, -1)
# Owner-side direct task dispatch (README "Ownership & direct dispatch"):
# owners lease workers from the controller and push plain-task specs to
# them directly, keeping the controller off the per-task hot path. False
# routes every plain task through controller dispatch (the classic path —
# also the failover target when a direct connection severs).
_flag("direct_dispatch", bool, True)
# Max leases granted/requested per batch (one grant amortizes over many
# tasks; the agent acquires a node's whole batch concurrently in one RPC).
_flag("lease_batch", int, 16)
# Idle lease lifecycle: owners return leases idle for this long, and the
# controller keeps returned leases warm in a per-node pool for the same
# window before telling the agent to unlease the worker (a regrant from
# the pool costs no agent round trip and usually no new owner connection).
_flag("lease_idle_s", float, 0.5)
# Streaming generators: executor pauses once this many yielded items are
# unacknowledged by the consumer (reference
# _generator_backpressure_num_objects); <=0 disables backpressure.
_flag("generator_backpressure_items", int, 64)
_flag("log_to_driver", bool, True)
# Device object plane (README "Device objects"): single-device jax.Arrays
# returned from tasks/actors (or put()) stay pinned in the producing
# process's DeviceObjectTable behind a placeholder ObjectRef instead of
# being copied through the host store; resolution is tiered (in-process
# zero-copy / same-host shm export / cross-host streamed fetch). False
# restores the host-store path everywhere, byte-identically.
_flag("device_objects", bool, True)
# Arrays below this ride the host path (inline) as before — pinning tiny
# arrays costs more bookkeeping than the copy it saves.
_flag("device_object_min_bytes", int, 100 * 1024)
# RPC write coalescing (see README "Transport"): frames buffer per
# connection and flush with ONE drain per event-loop burst. rpc_coalesce
# False restores the legacy one-drain-per-frame path; wbuf_high_bytes is
# the writer-backpressure high-water mark; parts up to join_bytes are
# joined into one transport write (larger oob buffers go zero-copy).
_flag("rpc_coalesce", bool, True)
_flag("rpc_wbuf_high_bytes", int, 4 << 20)
_flag("rpc_join_bytes", int, 128 << 10)
# Fixed-point resource arithmetic granularity (reference fixed_point.h uses 1e-4).
_flag("resource_unit", int, 10000)
# --- storage plane / checkpoint engine (README "Checkpointing & storage") --
# Async checkpointing: save_async snapshots device->host synchronously and
# streams shards to the storage backend off the step path; the manifest
# rename is the commit point. False restores fully synchronous saves
# (byte-identical output, report()/save() block until committed).
_flag("ckpt_async", bool, True)
# Keep-last-K retention enforced by the engine after each commit (pinned
# checkpoints — e.g. a PBT clone's restore source — are never collected).
# 0 = unlimited.
_flag("ckpt_keep", int, 0)
# Snapshot safety: host-view shard snapshots that do not own their memory
# (zero-copy views on CPU/TPU-host backends) are copied before save_async
# returns, so XLA buffer donation in the next step cannot corrupt the
# in-flight write. 0 = keep zero-copy views (donation-free loops only).
_flag("ckpt_snapshot_copy", bool, True)
# Transient storage failures (StorageTransientError: sim:// injected
# faults, real network blips) are retried this many times with exponential
# backoff starting at ckpt_retry_base_s before the save fails.
_flag("ckpt_retries", int, 4)
_flag("ckpt_retry_base_s", float, 0.05)
# Multi-rank commit: rank 0 waits this long for every rank's shard
# metadata to appear in storage before declaring the save failed (the
# barrier rides storage, not RPC — a crashed rank simply never commits).
_flag("ckpt_commit_timeout_s", float, 120.0)
# Uncommitted partial checkpoint dirs (no manifest) younger than this are
# presumed in-flight and skipped by GC; older ones are collected.
_flag("ckpt_partial_grace_s", float, 600.0)
# sim:// backend shaping (storage/sim.py): per-op latency, put/get
# bandwidth cap (GB/s, 0 = unlimited), and a hard "network partition"
# switch under which every op raises StorageTransientError.
_flag("sim_storage_latency_s", float, 0.0)
_flag("sim_storage_gbps", float, 0.0)
_flag("sim_storage_severed", bool, False)
# --- stall detection & flight recorder (README "Stall detection") ----------
# Escalation ladder thresholds, seconds of per-task progress silence before
# each stage fires: warn (StallReport only), dump (+ stack capture + flight
# dump through the storage plane), kill (+ the node agent fells the worker
# so the attempt fails over through the ordinary retry path). 0/unset
# disables that stage; with ALL stages off the watchdog thread never starts
# and nothing beacons — byte-identical to a watchdog-free build.
_flag("stall_warn_s", float, 0.0)
_flag("stall_dump_s", float, 0.0)
_flag("stall_kill_s", float, 0.0)
# Monitor/beacon cadence: the watchdog wakes (and beacons the node agent)
# this often while a task executes. The agent's backstop treats beacons
# STOPPING as the stall signal for workers too wedged to self-report.
_flag("stall_beacon_interval_s", float, 0.5)
# Flight recorder ring size (recent runtime events dumped into each
# StallReport); 0 disables recording entirely.
_flag("flight_recorder_events", int, 256)
# Storage-plane URI escalation dumps are written under (any backend:
# local://, mem://, sim://, bare path); "" = <session_dir>/<session>/flight.
# Train runs point their workers at <run>/flight via RT_STALL_FLIGHT_DIR.
_flag("stall_flight_dir", str, "")
# Per-op deadline for host-tier collectives (util.collective): a recv that
# waits longer than this aborts the op with CollectiveTimeoutError naming
# the op, group, and the peer it was waiting on. <=0 falls back to the
# module default (120s) — a wedged ring never hangs forever either way.
_flag("collective_timeout_s", float, 0.0)
# --- distributed tracing (README "Tracing & timeline") ----------------------
# Master switch for the causal tracing plane: spans from submit to decode,
# propagated through task/actor wire tuples and serve requests, exported as
# Perfetto timelines (`ray-tpu timeline`). Unset/False is byte-identical
# off: no contextvar writes on hot paths, no span ring, no rpc hook, and
# the wire tuples keep their pre-tracing arity (pinned by test).
_flag("tracing", bool, False)
# Head-based sampling: the decision is rolled ONCE at the trace root (a
# top-level submit or an ingress request) and carried by propagation —
# children never re-roll. 1.0 = trace everything.
_flag("trace_sample", float, 1.0)
# Per-process span ring capacity (flight-recorder idiom): spans beyond this
# between metrics-flush ticks drop oldest-first.
_flag("trace_buffer_spans", int, 4096)
# Controller-side trace index capacity: completed/evicted traces beyond
# this are dropped from memory (persisted ones remain readable from the
# storage plane).
_flag("trace_max_traces", int, 512)
# Storage-plane URI completed traces persist under (any backend; "" =
# <session_dir>/<session>/traces). "none" disables persistence.
_flag("trace_dir", str, "")
# Always-sample escalation for serve requests: an UNSAMPLED request slower
# than this records a root span anyway, so tail latency outliers stay
# visible under tight head sampling. <=0 disables the escalation.
_flag("trace_slow_s", float, 0.0)
# --- cluster telemetry & profiling (README "Telemetry & profiling") ---------
# Continuous resource sampling cadence: each node agent samples node
# CPU/mem/disk + per-worker RSS/CPU%, and each worker samples device-side
# series (jax HBM in-use/peak, compile count/seconds, device-object bytes)
# on this tick; samples piggyback on the existing agent heartbeats. <= 0 /
# unset disables the plane entirely: no sampler thread anywhere, heartbeat
# frames byte-identical (pinned by test).
_flag("telemetry_interval_s", float, 0.0)
# Controller-side retention: a per-(node, series) downsampling ring keeps
# raw recent points plus decimated history; series with no new point for
# window_s age out (a dead agent's series disappear instead of freezing).
_flag("telemetry_window_s", float, 600.0)
# Points kept per series tier (raw + decimated history each hold this many).
_flag("telemetry_points", int, 240)
# On-demand CPU profiling (`ray-tpu profile --mode cpu`): the in-process
# sampling profiler walks every worker thread's stack this many times per
# second for the capture window.
_flag("profile_hz", int, 100)
# Storage-plane URI captured profiles persist under (any backend);
# "" = <session_dir>/<session>/profiles.
_flag("profile_dir", str, "")
# --- cluster event plane (README "Cluster events") --------------------------
# Ring capacity for lifecycle events: the controller's arrival-order ring,
# each process's emission buffer, and the node agents' heartbeat-piggyback
# deques are all bounded by this. 0 disables the plane entirely (no rings,
# no `events=` keys on any frame); the default keeps it always-on — events
# are emitted at lifecycle-transition rate, never on the per-task hot path
# (pinned by the bench `events_overhead` lane).
_flag("events_buffer", int, 2048)
# Persist settled events through the storage plane as segmented JSONL under
# events_dir, so history survives controller restarts. False = in-memory
# ring only.
_flag("events_persist", bool, True)
# Storage-plane URI event segments land under (any backend: local://,
# mem://, sim://, bare path); "" = <session_dir>/<session>/events.
_flag("events_dir", str, "")
# Events per JSONL segment: a full segment is written once and never
# rewritten; the in-progress tail rewrites atomically each sweep tick.
_flag("events_segment_events", int, 512)
# Keep-last-K segment rotation: oldest segments beyond this are deleted.
_flag("events_keep_segments", int, 16)
# --- serving hot loop (README "Serving hot loop") ---------------------------
# Token-batch stream ring: streaming serve responses (SSE) ride a shm
# StreamRing from the replica straight to the HTTP proxy — one host hop
# per token BATCH instead of one ObjectRef round trip per token. False
# restores the per-item streaming-generator reply path byte-identically
# (pinned by test).
_flag("token_ring", bool, True)
# Per-stream ring capacity in bytes (bounded: a stalled SSE consumer
# parks the producer instead of buffering unboundedly; a record may be at
# most half this).
_flag("token_ring_bytes", int, 1 << 20)
# Continuous-engine prefill lane: admissions (bucketed prefill + first-
# token sample) dispatch on a dedicated thread and splice into the
# running batch at chunk boundaries, so a new request's prefill compile/
# dispatch never stalls the decode loop. False restores inline admission.
_flag("llm_prefill_lane", bool, True)
# --- serve admission control (README "Overload & admission control") --------
# Master switch for the serve admission/degradation plane: per-deployment
# concurrency budgets, bounded router queues with deadlines (sheds raise
# a typed BackPressureError -> HTTP 429/503 + Retry-After), the per-route
# token bucket, and jittered replica-death retries. False restores the
# pre-admission behavior byte-identically — no queue, no shed, no budget
# fields on routing frames (pinned by test).
_flag("serve_admission", bool, True)
# Default queue deadline (seconds) for deployments that do not set
# queue_deadline_s: a request that cannot be assigned a replica slot
# within this long is shed, not stalled. Matches the legacy assign
# timeout so default-on admission changes no existing behavior.
_flag("serve_queue_deadline_s", float, 30.0)
# HTTP proxy per-route token bucket refill rate (requests/second);
# 0 disables rate limiting. Excess requests get 429 + Retry-After
# before touching the router queue.
_flag("serve_rps", float, 0.0)
# Token bucket capacity: bursts up to this many requests pass at once
# before the refill rate governs.
_flag("serve_burst", int, 16)
# Per-request retry budget for replica-death (and cross-router
# replica-busy) assignment failures: the router re-assigns against
# surviving replicas up to this many times with jittered backoff.
_flag("serve_retries", int, 2)
# Base for the jittered exponential backoff between those retries.
_flag("serve_retry_base_s", float, 0.05)
# --- cross-host streaming & multi-proxy (README section of same name) -------
# Push-stream transport: when a replica cannot attach the same-host shm
# StreamRing (cross-host replica, no shared /dev/shm), token-batch records
# ride the rpc transport to the proxy's per-process stream hub instead of
# degrading to the per-item classic reply path. Same record contract,
# bounded send window, burst coalescing into single frames. False restores
# the nak -> per-item fallback for remote replicas.
_flag("stream_push", bool, True)
# Push-stream send window in bytes: the producer may have at most this
# many un-acknowledged record bytes in flight (the consumer credits bytes
# back as it drains). A stalled consumer parks the pump — bounded
# buffering, exactly like the shm ring. A record may be at most half this.
_flag("stream_window_bytes", int, 256 * 1024)
# Test/bench hook: replicas skip the same-host shm attach so the push
# transport is exercised on a single box (simulates a cross-host replica).
# Never set in production — shm is strictly cheaper when it is available.
_flag("stream_force_push", bool, False)
# Number of HTTP proxy processes serve.run starts (serve.run(num_proxies=)
# overrides). Proxy 0 binds the requested port, extras auto-bind; ports
# are discoverable via serve.proxy_ports(). All proxies share replica-set
# routing via the controller's versioned long-poll and run their own
# admission queues — the replica-side concurrency backstop keeps racing
# routers safe.
_flag("serve_proxies", int, 1)
# --- compiled dataflow graphs (README "Compiled graphs") --------------------
# Max invocations a compiled DAG keeps in flight: execute() returns a
# DagRef immediately and only blocks once this many invocations are still
# unfulfilled (per-invocation sequence numbers ride every edge, so stages
# stay in lockstep without a barrier).
_flag("dag_max_inflight", int, 8)
# Device-object edges: a stage output that is a large single-device
# jax.Array stays pinned in the producing stage's DeviceObjectTable and
# the channel carries only the ~200B placeholder — co-located consumers
# resolve it zero-copy (same process) or one-copy (same-host shm export)
# through the PR 7 tier ladder. False pickles every value through the shm
# ring, byte-identically to the host path.
_flag("dag_device_edges", bool, True)
# Compiled-driver stage-liveness monitor cadence: stage actor/worker death
# surfaces as a typed DagStageError on every in-flight DagRef within a few
# of these polls (plus the runtime's own death-detection latency).
_flag("dag_monitor_interval_s", float, 0.2)
# Per-edge shm channel capacity (one in-flight message per edge; a
# message may be at most this large).
_flag("dag_channel_bytes", int, 1 << 20)
# Device-edge eligibility threshold (bytes). DAG edges are pre-negotiated
# point-to-point with a bounded retention window, so the plane pays for
# itself on much smaller arrays than the general object plane's
# RT_DEVICE_OBJECT_MIN_BYTES — a pipeline-parallel decode step's
# activation is a few KB and must still ride as a placeholder.
_flag("dag_edge_min_bytes", int, 1024)
# --- pipeline-parallel serving (README "Pipeline-parallel serving") ---------
# Stage count for the OpenAI serving surface: >1 builds a PipelinedEngine
# (model split into this many DAG stage actors) behind the same
# submit()/GenStream API; 0/1 keeps the single-process ContinuousEngine.
_flag("pp_stages", int, 0)
# Microbatch SIZE (slots per microbatch) for the pipelined engine;
# 0 = auto (max_batch split into 2*n_stages microbatches, enough to keep
# every stage busy with headroom under RT_DAG_MAX_INFLIGHT).
_flag("pp_microbatch", int, 0)
# Consecutive graph-rebuild attempts after stage death before the engine
# gives up and drains every open stream with the attributed error.
_flag("pp_rebuild_max", int, 3)
# --- kernels / diagnostics --------------------------------------------------
# --- data plane (README "Data plane") ---------------------------------------
# Pipelined all-to-all exchange: map tasks push partition shards the moment
# they're produced and reduce-side merges start on first input (bounded
# fan-in). False restores the barrier exchange (all maps complete before any
# reduce submits) — kept as the bench A/B leg and an escape hatch.
_flag("data_pipelined_exchange", bool, True)
# Per-operator in-flight budget: at most this many block tasks are
# outstanding per executor stage (submission also brakes on the cluster
# store-backpressure signal, STORE_BACKPRESSURE_FRACTION).
_flag("data_max_inflight_blocks", int, 16)
# Reduce-side fan-in bound: when a partition has accumulated this many
# pending shards mid-exchange, they are consolidated by an incremental
# merge task — no reduce ever takes an unbounded argument list.
_flag("data_reduce_fanin", int, 8)
# Target bytes per block for file reads: small files group toward this
# size, files larger than it split into row-sliced read tasks, so the
# exchange has real parallelism regardless of the on-disk file layout.
_flag("data_block_bytes", int, 128 * 1024 * 1024)
# Exchange shard memory cap (bytes): a consolidated partition shard larger
# than this spills through the storage plane instead of staying in shm
# (0 disables size-triggered spill; store backpressure still forces it).
_flag("data_mem_cap_bytes", int, 0)
# Storage-plane URI exchange shards spill under (any backend: local://,
# mem://, sim://); "" = local://<session_dir>/data_spill. Spilled shards
# are restored transparently when the reduce consumes them.
_flag("data_spill_uri", str, "")
# Decode-attention kernel selection: "pallas" / "xla" force a path, ""
# keeps the size-based dispatch (ops/decode_attention.py
# PALLAS_MIN_CACHE_BYTES).
_flag("decode_kernel", str, "")
# Non-empty: worker processes run under cProfile and write
# <dir>/worker_<pid>.pstats at exit (dev profiling; costs ~2x on hot paths).
_flag("profile_worker", str, "")


class _Config:
    """Attribute access to flags, resolved in precedence order:

    1. explicit `init(_system_config={...})` overrides (this process)
    2. the process's own `RT_<NAME>` env var
    3. the cluster snapshot received at registration
    4. the registry default

    Env sits ABOVE the snapshot deliberately: the snapshot carries the
    controller-side resolved table to every node, but a per-process env
    injection (e.g. train pointing each worker's RT_STALL_FLIGHT_DIR at
    <run>/flight, or arming RT_PROFILE_WORKER on one worker) must win on
    that process — it is the most specific setting there is."""

    def __init__(self):
        self._overrides: dict[str, Any] = {}
        self._snapshot: dict[str, Any] = {}

    def apply_system_config(self, overrides: dict[str, Any] | None) -> None:
        if not overrides:
            return
        for k, v in overrides.items():
            if k not in _REGISTRY:
                raise ValueError(f"Unknown system config flag: {k}")
            typ, _ = _REGISTRY[k]
            self._overrides[k] = typ(v)

    def snapshot(self) -> dict[str, Any]:
        """Full resolved table — propagated to all nodes at cluster start
        (cf. reference NodeManager GetSystemConfig node_manager.proto:451)."""
        return {k: getattr(self, k) for k in _REGISTRY}

    def load_snapshot(self, snap: dict[str, Any]) -> None:
        self._snapshot.update(snap)

    def __getattr__(self, name: str):
        if name.startswith("_"):
            raise AttributeError(name)
        if name in self._overrides:
            return self._overrides[name]
        if name not in _REGISTRY:
            raise AttributeError(f"Unknown config flag {name}")
        typ, default = _REGISTRY[name]
        env = os.environ.get(f"RT_{name.upper()}")
        if env is not None:
            if typ is bool:
                return env.lower() in ("1", "true", "yes")
            if typ in (dict, list):
                return json.loads(env)
            return typ(env)
        if name in self._snapshot:
            return self._snapshot[name]
        return default


CONFIG = _Config()


def stack_dump_path(session_id: str, pid: int) -> str:
    """Where a worker's faulthandler stack dumps land (written by
    worker_proc's SIGUSR1 registration, read back by the node agent for
    /api/stacks). ONE definition so the two sides can't drift."""
    return os.path.join(CONFIG.session_dir, session_id, "stacks",
                        f"{pid}.txt")
