"""Cluster telemetry: continuous node/worker resource sampling + on-demand
in-process profiling.

Parity target: the reference's reporter plane (dashboard/modules/reporter/
reporter_agent.py streams per-node CPU/mem/GPU samples into the metrics
head; its profiling endpoints serve on-demand py-spy captures of live
workers). Here the plane rides existing seams instead of new daemons:

- sampling: armed by RT_TELEMETRY_INTERVAL_S (unset => NO sampler thread
  anywhere and heartbeat frames stay byte-identical — the PR 9/11
  zero-cost-when-off pattern). The node agent samples node CPU/mem/disk and
  per-worker RSS/CPU% from /proc on its own loop; each worker samples
  device-side series (jax `memory_stats()` HBM bytes, live compile
  count/seconds via a `jax.monitoring` listener, device-object-plane bytes
  from device_store) on a daemon thread and pushes them to its agent.
- transport: samples piggyback on the existing agent->controller heartbeats
  (`telemetry` key, batched) — no new connection or cadence, same as the
  PR 11 span drain.
- profiling: `sample_profile()` is the worker-side CPU sampling profiler
  behind `ray-tpu profile --mode cpu` — sys._current_frames() walked at
  RT_PROFILE_HZ for the capture window, rendered as collapsed stacks plus
  Chrome-trace flame events (the generalization of the per-pid SIGUSR1
  one-shot stack dump into a timed sampler).

Everything here is stdlib + /proc reads; jax and device_store are observed
through sys.modules gates so a process that never imported them never pays
(or triggers) the import.
"""

from __future__ import annotations

import os
import sys
import threading
import time
from typing import Callable, Optional

from ray_tpu._private.rtconfig import CONFIG


def interval_s() -> float:
    """Sampling cadence; <= 0 means the telemetry plane is OFF."""
    try:
        return float(CONFIG.telemetry_interval_s)
    except (TypeError, ValueError):
        return 0.0


_PAGE_SIZE = os.sysconf("SC_PAGE_SIZE") if hasattr(os, "sysconf") else 4096
_CLK_TCK = os.sysconf("SC_CLK_TCK") if hasattr(os, "sysconf") else 100


class CpuTracker:
    """Whole-node CPU utilization percent from /proc/stat deltas between
    successive percent() calls (first call returns 0.0 — no window yet)."""

    def __init__(self):
        self._last: Optional[tuple] = None  # (busy_jiffies, total_jiffies)

    @staticmethod
    def _read() -> Optional[tuple]:
        try:
            with open("/proc/stat") as f:
                line = f.readline()
        except OSError:
            return None
        parts = line.split()
        if not parts or parts[0] != "cpu":
            return None
        vals = [int(v) for v in parts[1:]]
        idle = vals[3] + (vals[4] if len(vals) > 4 else 0)  # idle + iowait
        total = sum(vals)
        return (total - idle, total)

    def percent(self) -> float:
        cur = self._read()
        if cur is None:
            return 0.0
        last, self._last = self._last, cur
        if last is None or cur[1] <= last[1]:
            return 0.0
        busy = cur[0] - last[0]
        total = cur[1] - last[1]
        return round(100.0 * max(0, busy) / max(1, total), 2)


class PidCpuTracker:
    """Per-pid CPU percent from /proc/<pid>/stat utime+stime deltas.
    Tracks many pids; entries for pids not seen in a sweep are pruned."""

    def __init__(self):
        self._last: dict[int, tuple] = {}  # pid -> (jiffies, monotonic)

    @staticmethod
    def _read_jiffies(pid: int) -> Optional[int]:
        try:
            with open(f"/proc/{pid}/stat") as f:
                data = f.read()
        except OSError:
            return None
        # comm may contain spaces/parens: fields start after the last ')'.
        try:
            rest = data[data.rindex(")") + 2:].split()
            return int(rest[11]) + int(rest[12])  # utime + stime
        except (ValueError, IndexError):
            return None

    def percent(self, pid: int) -> float:
        jif = self._read_jiffies(pid)
        now = time.monotonic()
        if jif is None:
            self._last.pop(pid, None)
            return 0.0
        last = self._last.get(pid)
        self._last[pid] = (jif, now)
        if last is None or now <= last[1]:
            return 0.0
        dt = now - last[1]
        return round(100.0 * max(0, jif - last[0]) / _CLK_TCK / dt, 2)

    def prune(self, live_pids) -> None:
        live = set(live_pids)
        for pid in [p for p in self._last if p not in live]:
            self._last.pop(pid, None)


def pid_rss_bytes(pid: int) -> Optional[int]:
    try:
        with open(f"/proc/{pid}/statm") as f:
            fields = f.read().split()
        return int(fields[1]) * _PAGE_SIZE
    except (OSError, ValueError, IndexError):
        return None


def mem_percent() -> float:
    """Node memory utilization percent (MemTotal vs MemAvailable)."""
    total = avail = None
    try:
        with open("/proc/meminfo") as f:
            for line in f:
                if line.startswith("MemTotal:"):
                    total = int(line.split()[1])
                elif line.startswith("MemAvailable:"):
                    avail = int(line.split()[1])
                if total is not None and avail is not None:
                    break
    except OSError:
        return 0.0
    if not total or avail is None:
        return 0.0
    return round(100.0 * (1.0 - avail / total), 2)


def disk_percent(path: str) -> float:
    try:
        st = os.statvfs(path)
    except OSError:
        return 0.0
    total = st.f_blocks * st.f_frsize
    free = st.f_bavail * st.f_frsize
    if total <= 0:
        return 0.0
    return round(100.0 * (1.0 - free / total), 2)


# --------------------------------------------------------- compile events
# Live jax compile telemetry: a jax.monitoring duration listener counts
# backend compiles and their cumulative seconds from the moment the worker
# sampler first observes jax imported. Registration is idempotent and
# NEVER imports jax itself (sys.modules gate — pool workers that stay
# jax-free must not pay the ~2s plugin import for a gauge).
_compile_lock = threading.Lock()
_compile_stats = {"count": 0, "seconds": 0.0}
_compile_listener_installed = False

_COMPILE_EVENT = "/jax/core/compile/backend_compile_duration"


def _on_compile_event(event: str, duration: float, **kw) -> None:
    if event != _COMPILE_EVENT:
        return
    with _compile_lock:
        _compile_stats["count"] += 1
        _compile_stats["seconds"] += float(duration)


def ensure_compile_listener() -> bool:
    """Register the compile-duration listener iff jax is ALREADY imported.
    Returns True once installed. Compiles that happened before the first
    armed sample are not counted (the listener cannot observe the past)."""
    global _compile_listener_installed
    if _compile_listener_installed:
        return True
    jax = sys.modules.get("jax")
    if jax is None:
        return False
    try:
        jax.monitoring.register_event_duration_secs_listener(_on_compile_event)
    except Exception:
        return False
    _compile_listener_installed = True
    return True


def compile_stats() -> dict:
    with _compile_lock:
        return dict(_compile_stats)


# ------------------------------------------------------- worker-side sampler
class WorkerSampler:
    """Daemon thread inside a worker process sampling device-side series and
    pushing them to the node agent (worker_telemetry). Started by
    worker_proc ONLY when RT_TELEMETRY_INTERVAL_S is set — with the plane
    off this class is never instantiated (no thread, pinned by test)."""

    THREAD_NAME = "rt-telemetry"

    def __init__(self, push: Callable[[dict], None], interval: float):
        self._push = push
        self._interval = max(0.05, interval)
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def start(self) -> None:
        self._thread = threading.Thread(
            target=self._loop, daemon=True, name=self.THREAD_NAME)
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()

    def _loop(self) -> None:
        while not self._stop.wait(self._interval):
            try:
                series = self.sample()
            except Exception:
                continue  # a bad sample tick must never kill the thread
            if series:
                try:
                    self._push(series)
                except Exception:
                    pass  # agent away; next tick retries

    @staticmethod
    def sample() -> dict:
        """One device-side sample. Every source is sys.modules-gated: a
        worker that never touched jax or the device plane reports nothing
        for those series (and never triggers their import)."""
        out: dict = {}
        if ensure_compile_listener():
            st = compile_stats()
            out["compile_count"] = st["count"]
            out["compile_s"] = round(st["seconds"], 4)
        jax = sys.modules.get("jax")
        # Gate on the backend being ALREADY initialized, not merely jax
        # being imported: local_devices() on a cold backend would trigger
        # full runtime init from the sampler thread — on TPU hosts that
        # acquires the chips (exclusive!) for a worker that may never
        # compute on them, and blocks the tick for seconds.
        xb = sys.modules.get("jax._src.xla_bridge")
        if jax is not None and xb is not None \
                and getattr(xb, "_backends", None):
            used = peak = 0
            have = False
            try:
                for d in jax.local_devices():
                    ms = d.memory_stats()
                    if not ms:
                        continue  # CPU backends report no memory stats
                    have = True
                    used += int(ms.get("bytes_in_use") or 0)
                    peak += int(ms.get("peak_bytes_in_use")
                                or ms.get("bytes_in_use") or 0)
            except Exception:
                have = False
            if have:
                out["hbm_used"] = used
                out["hbm_peak"] = peak
        ds = sys.modules.get("ray_tpu._private.device_store")
        if ds is not None:
            try:
                st = ds.table_stats()
                out["device_bytes"] = int(st.get("bytes") or 0)
            except Exception:
                pass
        eng = sys.modules.get("ray_tpu.llm.engine")
        if eng is not None:
            # Live decode throughput (README "Serving hot loop"): tokens
            # delivered to stream consumers since the previous tick. Only
            # workers that actually host a continuous engine ever import
            # the module, so everyone else skips the series entirely.
            try:
                out["llm.tokens_per_s"] = round(
                    eng.tokens_per_s_snapshot(), 2)
            except Exception:
                pass
        xch = sys.modules.get("ray_tpu.data._internal.exchange")
        if xch is not None:
            # Exchange pressure (README "Data plane"): blocks in flight,
            # bytes spilled through the storage plane, and submit-loop
            # backpressure stalls. The module only loads in processes that
            # drive or execute an exchange.
            try:
                st = xch.exchange_stats()
                out["data.blocks_inflight"] = st["blocks_inflight"]
                out["data.spilled_bytes"] = st["spilled_bytes"]
                out["data.bp_stalls"] = st["bp_stalls"]
            except Exception:
                pass
        pp = sys.modules.get("ray_tpu.llm.pipeline")
        if pp is not None:
            # Pipeline-stage occupancy (README "Pipeline-parallel
            # serving"): busy fraction of this process's stage(s) since
            # the previous tick — the bubble is its complement. Only
            # processes hosting a PipelineStage import the module.
            try:
                occ = pp.occupancy_snapshot("telemetry")
                if occ:
                    out["llm.pp_occupancy"] = round(max(occ.values()), 3)
            except Exception:
                pass
        return out


# --------------------------------------------------- CPU sampling profiler
#: Raw stack snapshots kept per capture (~KBs each across a worker's
#: threads): bounds capture RSS at tens of MB worst case.
_MAX_PROFILE_SAMPLES = 20_000


def clamp_profile_seconds(seconds) -> float:
    """One capture-window clamp shared by every hop of the profile path
    (controller -> agent -> worker): 0.05s floor, 300s cap, 5s default.
    The hops' RPC timeout margins (+40s controller, +30s agent) are tuned
    against these constants — change them here, nowhere else."""
    try:
        seconds = float(seconds)
    except (TypeError, ValueError):
        seconds = 5.0  # unset/garbage -> default; explicit 0 clamps to floor
    return min(300.0, max(0.05, seconds))


def sample_profile(seconds: float, hz: Optional[int] = None,
                   exclude_thread: Optional[int] = None) -> dict:
    """In-process CPU sampling profile over ALL of this process's threads:
    sys._current_frames() walked at `hz` for `seconds`, folded into
    collapsed stacks (root;...;leaf -> sample count, the flamegraph input)
    and reconstructed into Chrome-trace flame events (one lane per thread;
    consecutive samples sharing a frame prefix merge into one "X" event).
    `exclude_thread` drops the sampler's own lane. Runs on a caller-owned
    thread — the capture loop sleeps between samples."""
    if hz is None:
        try:
            hz = int(CONFIG.profile_hz)
        except (TypeError, ValueError):
            hz = 100
    hz = max(1, min(1000, int(hz)))
    seconds = max(0.05, float(seconds))
    period = 1.0 / hz
    me = threading.get_ident()
    names = {t.ident: t.name for t in threading.enumerate()}
    samples: list[tuple[float, dict]] = []  # (t_rel, tid -> stack tuple)
    t0 = time.monotonic()
    deadline = t0 + seconds
    while True:
        now = time.monotonic()
        if now >= deadline or len(samples) >= _MAX_PROFILE_SAMPLES:
            # The raw-snapshot buffer is bounded: profiling must never
            # OOM the live worker it is observing (an extreme
            # seconds x hz request ends early with what it has; the
            # returned `seconds` reflects the actual window).
            break
        frames = sys._current_frames()
        snap: dict[int, tuple] = {}
        for tid, frame in frames.items():
            if tid == me or tid == exclude_thread:
                continue
            stack = []
            f = frame
            depth = 0
            while f is not None and depth < 128:
                code = f.f_code
                stack.append(f"{code.co_name} "
                             f"({os.path.basename(code.co_filename)}:"
                             f"{f.f_lineno})")
                f = f.f_back
                depth += 1
            snap[tid] = tuple(reversed(stack))  # root -> leaf
        samples.append((now - t0, snap))
        time.sleep(max(0.0, period - (time.monotonic() - now)))
    duration = time.monotonic() - t0

    collapsed: dict[str, int] = {}
    for _, snap in samples:
        for stack in snap.values():
            key = ";".join(stack)
            collapsed[key] = collapsed.get(key, 0) + 1
    events = _flame_events(samples, names, period)
    return {
        "mode": "cpu",
        "pid": os.getpid(),
        "hz": hz,
        "seconds": round(duration, 3),
        "samples": len(samples),
        "threads": sorted({tid for _, s in samples for tid in s}),
        "collapsed": collapsed,
        "traceEvents": events,
    }


def _flame_events(samples: list, names: dict, period: float) -> list[dict]:
    """Merge per-thread sample stacks into Chrome-trace complete events: at
    each depth, a run of consecutive samples sharing the same frame (and
    the same ancestry) becomes one "X" event. Timestamps are relative
    microseconds; lanes (tid) are OS thread ids with name metadata."""
    by_tid: dict[int, list[tuple[float, tuple]]] = {}
    for t, snap in samples:
        for tid, stack in snap.items():
            by_tid.setdefault(tid, []).append((t, stack))
    events: list[dict] = []
    lane = 0
    for tid, rows in by_tid.items():
        lane += 1
        events.append({"ph": "M", "name": "thread_name", "pid": 1,
                       "tid": lane,
                       "args": {"name": f"{names.get(tid) or tid}"}})
        open_ev: list[dict] = []  # stack of open events, one per depth
        prev: tuple = ()
        for i, (t, stack) in enumerate(rows):
            # Close events where the frame (or an ancestor) changed.
            common = 0
            while (common < len(prev) and common < len(stack)
                   and prev[common] == stack[common]):
                common += 1
            end_us = t * 1e6
            while len(open_ev) > common:
                ev = open_ev.pop()
                ev["dur"] = max(1.0, end_us - ev["ts"])
            for d in range(common, len(stack)):
                ev = {"ph": "X", "name": stack[d], "cat": "sample",
                      "pid": 1, "tid": lane, "ts": t * 1e6, "dur": 1.0}
                events.append(ev)
                open_ev.append(ev)
            prev = stack
        tail = (rows[-1][0] + period) * 1e6 if rows else 0.0
        while open_ev:
            ev = open_ev.pop()
            ev["dur"] = max(1.0, tail - ev["ts"])
    return events


def jax_profile(seconds: float) -> dict:
    """Capture a jax.profiler trace window (XLA/TPU device timeline) and
    return it as a zip archive blob. Requires jax in the worker; the
    caller surfaces failures as attributed errors."""
    import io
    import shutil
    import tempfile
    import zipfile

    import jax

    seconds = max(0.05, float(seconds))
    d = tempfile.mkdtemp(prefix="rt-jaxprof-")
    try:
        jax.profiler.start_trace(d)
        time.sleep(seconds)
        jax.profiler.stop_trace()
        buf = io.BytesIO()
        nfiles = 0
        with zipfile.ZipFile(buf, "w", zipfile.ZIP_DEFLATED) as z:
            for root, _, files in os.walk(d):
                for name in files:
                    p = os.path.join(root, name)
                    z.write(p, os.path.relpath(p, d))
                    nfiles += 1
        return {"mode": "jax", "pid": os.getpid(),
                "seconds": round(seconds, 3), "files": nfiles,
                "archive": buf.getvalue()}
    finally:
        shutil.rmtree(d, ignore_errors=True)


def default_profile_dir(session_id: str) -> str:
    d = CONFIG.profile_dir
    if d:
        return d
    return os.path.join(CONFIG.session_dir, session_id, "profiles")
