"""Cluster event plane: durable, causal lifecycle events.

Parity target: the reference's event framework (src/ray/util/event.h, the
dashboard's `list_cluster_events` state API, and the export-event sinks).
PR 11 (tracing) answers "where did this request's time go" and PR 12
(telemetry) answers "what is the cluster doing right now"; this plane
answers "what happened and why" AFTER the fact — a dead actor, a fenced
node, a stall kill — without grepping per-process logs.

One Event record per lifecycle transition the runtime already knows about:

    {"seq":  int,      # controller-minted, monotonic arrival order
     "ts":   float,    # emission wall time
     "sev":  str,      # debug | info | warning | error
     "kind": str,      # a key of the KINDS registry below
     "src":  str,      # emitting process label (worker id / pidN / node id)
     "node": str|None, # node the event is about (filled at ingest when the
                       # frame arrived on a node connection)
     "entity": [str],  # ids this event explains: actor/worker/task/lease/
                       # node/job/run ids — `list_events(entity=)` matches
                       # any of them by prefix
     "msg":  str,
     "attrs": {...},      # optional, kind-specific (e.g. {"cause": "crash"})
     "trace_id": str|None # optional PR 11 linkage: `ray-tpu events` ->
                          # `ray-tpu timeline --trace` chains
    }

Life of an event:

- worker/driver side: `emit_event` appends to a bounded per-process ring;
  the ring drains to the controller piggybacked on the existing 1 Hz
  metrics-flush batches (`events=` key — the PR 11 span-drain idiom, no
  new connection or cadence).
- node-agent side: the agent keeps its own bounded pending deque; batches
  ride heartbeat frames (and worker_died pushes, so an exit event's seq
  always precedes the restart/failover events its processing mints —
  causal chains stay ordered under arrival-order seq minting).
- controller side: events index into a bounded arrival ring plus a
  per-entity secondary index; settled events persist through the storage
  plane (PR 8) under `<session>/events/` as segmented JSONL with
  keep-last-K rotation, so history survives controller snapshot/restore
  (the snapshot carries the seq counter; restore also scans the persisted
  segments so a restored head can never re-mint colliding seqs).

Surfaces: `util.state.list_events(entity=, kind=, severity=, since=)`,
`ray-tpu events [--follow] [--entity ID]`, the dashboard's `/api/events` +
recent-events panel, and error enrichment — ActorDiedError /
ObjectLostError messages name the event seq range that explains them.

Cost discipline (pinned by the bench `events_overhead` lane): emission is
always-on but BOUNDED — every ring is a deque with a cap, and nothing on
the per-task hot path emits (lifecycle transitions are orders of magnitude
rarer than tasks). RT_EVENTS_BUFFER=0 disables the plane entirely: no
ring, no `events=` keys on any frame, `enabled()` is one cached bool.
"""

from __future__ import annotations

import os
import threading
import time
from collections import deque
from typing import Optional

from ray_tpu._private.rtconfig import CONFIG

SEVERITIES = ("debug", "info", "warning", "error")

#: The kind registry: every `emit_event(kind=...)` literal in ray_tpu/ MUST
#: be declared here (enforced by the rtcheck `event-kinds` pass — a typo'd
#: kind would be unqueryable forever). kind -> (default severity, what the
#: event marks).
KINDS: dict[str, tuple[str, str]] = {
    # --- node lifecycle (controller-emitted) -------------------------------
    "node_register": ("info", "a node agent registered a fresh life"),
    "node_reconciled": ("info", "a SUSPECT/known node re-registered and was "
                                "reconciled in place"),
    "node_suspect": ("warning", "a node's control connection closed; frozen "
                                "for the suspicion grace window"),
    "node_dead": ("error", "a node was declared dead"),
    "incarnation_fenced": ("warning", "a message/lease from a previous node "
                                      "incarnation was rejected"),
    # --- worker lifecycle (agent-emitted) ----------------------------------
    "worker_start": ("debug", "a worker process was spawned"),
    "worker_exit": ("info", "a worker process exited (attrs.cause carries "
                            "the normalized exit cause)"),
    # --- actors (controller-emitted) ---------------------------------------
    "actor_create": ("info", "an actor creation was accepted"),
    "actor_ready": ("info", "an actor instance came up (created, restarted, "
                            "or re-bound after a blip)"),
    "actor_restart": ("warning", "an actor instance died and a restart was "
                                 "queued"),
    "actor_death": ("error", "an actor is permanently dead"),
    # --- direct-dispatch lease plane ---------------------------------------
    "lease_failover": ("warning", "a leased worker died; its lease was "
                                  "invalidated and in-flight specs fail "
                                  "over"),
    "lease_dedup_replay": ("info", "an agent replayed a recorded outcome "
                                   "for a failover re-dispatch (exactly-"
                                   "once dedup)"),
    # --- device object plane -----------------------------------------------
    "device_objects_lost": ("warning", "a producer died taking its pinned "
                                       "device objects with it"),
    # --- storage / checkpoints (worker-emitted) ----------------------------
    "checkpoint_commit": ("info", "a checkpoint manifest committed"),
    "checkpoint_gc": ("debug", "checkpoint retention/GC deleted a "
                               "checkpoint directory"),
    # --- train / serve (driver- and replica-worker-emitted) ----------------
    "train_restart": ("warning", "a train worker group failed and restarts "
                                 "from the latest committed checkpoint"),
    "serve_deploy": ("info", "a serve deployment was created or updated"),
    "serve_scale": ("info", "a serve deployment's replica target changed"),
    "serve_replica_death": ("warning", "a serve replica failed its health "
                                       "check or failed to start"),
    "serve_overload": ("warning", "a serve deployment's router queue "
                                  "saturated and began shedding (first "
                                  "shed after a quiet period)"),
    "serve_shed": ("warning", "serve admission control shed requests "
                              "(throttled aggregate; attrs carry the "
                              "per-reason counts since the last event)"),
    "serve_proxy_join": ("info", "a serve HTTP proxy came up and joined "
                                 "the controller's proxy registry"),
    "serve_stream_sever": ("warning", "a push-stream link was severed (or "
                                      "lost a frame) mid-stream; the SSE "
                                      "client got an attributed error"),
    # --- compiled dataflow graphs (driver-emitted) -------------------------
    "dag_compiled": ("info", "a DAG was compiled into persistent stage "
                             "loops wired by pre-negotiated shm channels"),
    "dag_stage_death": ("error", "a compiled-DAG stage died mid-run "
                                 "(attrs.stage names it); every in-flight "
                                 "invocation failed with DagStageError"),
    "dag_teardown": ("info", "a compiled DAG tore down; all stage loops "
                             "stopped and every channel was unlinked"),
    # --- data plane exchanges (driver-emitted) -----------------------------
    "data_exchange": ("info", "an all-to-all exchange (shuffle/sort/"
                              "repartition) completed; attrs carry map/"
                              "partition counts and spilled bytes"),
    "data_spill": ("warning", "an exchange spilled shards through the "
                              "storage plane under memory pressure"),
    # --- jobs (controller-emitted) -----------------------------------------
    "job_start": ("info", "a job driver subprocess was launched"),
    "job_stop": ("info", "a job reached a terminal state"),
    # --- watchdog escalation (controller-emitted on StallReport ingest) ----
    "stall": ("warning", "a stall-escalation stage was crossed (attrs.stage "
                         "= warn|dump|kill; carries the stalled task's "
                         "trace_id)"),
    # --- the plane's own bookkeeping ---------------------------------------
    "events_dropped": ("warning", "the persistence buffer overflowed while "
                                  "the backend was unreachable; oldest "
                                  "events were shed"),
}


# --------------------------------------------------------------------------
# Worker-exit cause enum — ONE vocabulary shared by worker_died reports,
# events (worker_exit attrs.cause), lease_invalid causes, and StallReports,
# so `ray-tpu events` queries by cause actually match across planes
# (previously: "oom"/"stall"/None/free-text reasons depending on the path).
# --------------------------------------------------------------------------
CAUSE_CRASH = "crash"          # unexpected process exit (incl. signals)
CAUSE_OOM = "oom"              # felled by the node memory monitor
CAUSE_STALL = "stall"          # felled by the stall-watchdog kill stage
CAUSE_IDLE_REAP = "idle_reap"  # idle pool worker collected by the reaper
CAUSE_KILLED = "killed"        # explicit kill (ray_tpu.kill, force-cancel)
CAUSE_SHUTDOWN = "shutdown"    # clean exit (code 0 / session teardown)

EXIT_CAUSES = (CAUSE_CRASH, CAUSE_OOM, CAUSE_STALL, CAUSE_IDLE_REAP,
               CAUSE_KILLED, CAUSE_SHUTDOWN)


def normalize_exit_cause(cause: Optional[str], reason: str = "") -> str:
    """Collapse the historical per-path cause spellings (raw signal ints,
    "killed" vs "stall", None-with-a-reason-string) into the enum above."""
    if cause in EXIT_CAUSES:
        return cause
    r = (str(cause or "") + " " + (reason or "")).lower()
    if "oom" in r or "memory monitor" in r:
        return CAUSE_OOM
    if "stall" in r:
        return CAUSE_STALL
    if "idle" in r and "reap" in r:
        return CAUSE_IDLE_REAP
    if "kill" in r or "cancel" in r:
        return CAUSE_KILLED
    if "exit code 0" in r or "shutdown" in r or "disconnect" in r:
        return CAUSE_SHUTDOWN
    return CAUSE_CRASH


# --------------------------------------------------------------------------
# Per-process emission ring (drained by the metrics flusher — the tracing
# span-ring idiom from _private/tracing.py).
# --------------------------------------------------------------------------
_ON: Optional[bool] = None  # cached enabled flag (None = unresolved)
_ring: Optional[deque] = None
_ring_lock = threading.Lock()
_pid = os.getpid()
_proc_label: Optional[str] = None


def enabled() -> bool:
    global _ON
    if _ON is None:
        try:
            _ON = int(CONFIG.events_buffer) > 0
        except Exception:
            _ON = True
    return _ON


def refresh() -> None:
    """Re-resolve the enabled flag after Worker.connect loads the cluster
    config snapshot (so `_system_config={"events_buffer": 0}` reaches every
    process), mirroring tracing.refresh()."""
    global _ON
    try:
        _ON = int(CONFIG.events_buffer) > 0
    except Exception:
        _ON = True
    if not _ON and _ring:
        _ring.clear()


def _get_ring() -> deque:
    global _ring
    ring = _ring
    if ring is None:
        with _ring_lock:
            if _ring is None:
                try:
                    cap = int(CONFIG.events_buffer)
                except Exception:
                    cap = 2048
                _ring = deque(maxlen=max(64, cap))
            ring = _ring
    return ring


def proc_label() -> str:
    """This process's display label (worker-id prefix, or pidN before a
    Worker exists — pidN is never cached so it can upgrade later). Shared
    by the event AND span records (tracing delegates here — one caching
    subtlety, one implementation)."""
    global _proc_label
    lbl = _proc_label
    if lbl is None:
        try:
            from ray_tpu._private.worker import global_worker

            w = global_worker()
            lbl = w.worker_id[:12] if w is not None else f"pid{_pid}"
        except Exception:
            lbl = f"pid{_pid}"
        if not lbl.startswith("pid"):
            _proc_label = lbl  # worker id is stable; pidN may upgrade later
    return lbl


def drain_ring(ring: Optional[deque]) -> list:
    """Pop everything off a piggyback ring (popleft-until-empty: concurrent
    producer appends during the drain land in the NEXT batch instead of
    racing a len() snapshot)."""
    if not ring:
        return []
    out = []
    try:
        while True:
            out.append(ring.popleft())
    except IndexError:
        pass
    return out


def build_event(kind: str, message: str = "", *,
                severity: Optional[str] = None,
                entity=(), node_id: Optional[str] = None,
                trace_id: Optional[str] = None,
                attrs: Optional[dict] = None,
                src: Optional[str] = None) -> dict:
    """One Event record (seq-less; the controller mints seq at ingest)."""
    ev: dict = {
        "ts": time.time(),
        "sev": severity or (KINDS.get(kind, ("info", ""))[0]),
        "kind": kind,
        "src": src or proc_label(),
        "node": node_id,
        "entity": [str(e) for e in entity if e],
        "msg": message,
    }
    if attrs:
        ev["attrs"] = attrs
    if trace_id:
        ev["trace_id"] = trace_id
    return ev


def emit_event(kind: str, message: str = "", *,
               severity: Optional[str] = None,
               entity=(), node_id: Optional[str] = None,
               trace_id: Optional[str] = None,
               attrs: Optional[dict] = None) -> None:
    """Append one lifecycle event to this process's ring; it reaches the
    controller on the next metrics-flush tick. No-op when the plane is
    disabled (RT_EVENTS_BUFFER=0)."""
    if not enabled():
        return
    _get_ring().append(build_event(
        kind, message, severity=severity, entity=entity, node_id=node_id,
        trace_id=trace_id, attrs=attrs))
    try:
        from ray_tpu.util import metrics

        metrics.ensure_flusher()
    except Exception:
        pass


def drain() -> list:
    """Pop all buffered events (called from the metrics flusher)."""
    return drain_ring(_ring)


def requeue_front(ring: Optional[deque], items: Optional[list],
                  lock: Optional[threading.Lock] = None) -> None:
    """ONE shed-oldest requeue discipline for every bounded piggyback ring
    (process event/span rings, the agent's heartbeat deques): put drained-
    but-unsent items back at the FRONT via per-item appendleft while the
    ring has headroom, stopping when full — the remaining (OLDEST) items
    shed, never entries appended since the drain. A naive extendleft
    would evict the freshest off the right end on overflow; a
    list/clear/extend rebuild would silently drop a producer's concurrent
    append (producers never hold a lock — appends are single GIL-atomic
    deque ops on hot paths). `lock` only excludes concurrent REQUEUES of
    the same ring."""
    if ring is None or not items:
        return
    if lock is not None:
        with lock:
            _requeue_items(ring, items)
    else:
        _requeue_items(ring, items)


def _requeue_items(ring: deque, items: list) -> None:
    for it in reversed(items):
        if ring.maxlen is not None and len(ring) >= ring.maxlen:
            return  # full of fresher entries: the older remainder sheds
        ring.appendleft(it)


def requeue(events: list) -> None:
    """Put drained-but-unsent events back at the FRONT of the ring (the
    metrics flusher raced a shutdown) so the forced final flush still
    delivers them."""
    requeue_front(_ring, events, _ring_lock)


def default_events_dir(session_id: str) -> str:
    return os.path.join(CONFIG.session_dir, session_id, "events")
