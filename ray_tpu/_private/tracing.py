"""Distributed tracing: causal spans from submit to decode.

Parity target: the role OpenTelemetry + the timeline half of the dashboard
plays in the reference (python/ray/util/tracing/ hooks task/actor calls with
propagated trace contexts; the dashboard renders task timelines). Here the
plane is runtime-native: a `TraceContext` (trace_id, span_id) minted at the
root — a driver-side submit or a serve HTTP request — rides a contextvar
through user code and the compact task/actor wire tuples, so every hop a
request makes (submit -> lease dispatch -> execute -> nested calls -> RPC
frames -> collective steps -> device-object resolution -> storage ops ->
engine decode iterations) lands as a span in one causally linked tree.

Life of a span:

- worker side: `record_span` appends to a bounded per-process ring (the
  flight-recorder idiom from _private/watchdog.py); the ring drains to the
  controller piggybacked on the existing metrics-flusher batches (one push
  per flush tick, no new connection or cadence).
- controller side: spans index per trace_id in a bounded ring; completed
  traces persist through the storage plane (PR 8) under
  `<session>/traces/<trace_id>.json` and export as Chrome-trace-event /
  Perfetto JSON via `ray-tpu timeline`, `util.state.list_traces()` /
  `get_trace()`, and the dashboard's `/api/traces`.

Cost discipline (pinned by test + the bench `tracing_overhead` lane):

- RT_TRACING unset: byte-identical off. `enabled()` is one cached-bool
  check; no contextvar is ever written, no ring exists, the rpc trace hook
  stays None (the same zero-cost-when-off pattern as the fault injector and
  the PR 9 flight hook), and the wire tuples keep their pre-tracing arity.
- RT_TRACING=1, request unsampled (head-based `RT_TRACE_SAMPLE` decided at
  the ROOT and carried by propagation — children never re-roll): one
  contextvar read + one random() per root, nothing else.
- sampled: spans are dict appends to a deque; draining rides the metrics
  flusher.

Escalation overrides head sampling where it matters: serve requests slower
than RT_TRACE_SLOW_S record a root span even when unsampled, and stall
reports carry the wedged task's trace id so a `ray-tpu stalls` hit links
straight to its timeline.
"""

from __future__ import annotations

import os
import random
import sys
import threading
import time
from collections import deque
from contextlib import contextmanager
from contextvars import ContextVar
from typing import Any, Optional

from ray_tpu._private.ids import random_id_bytes
from ray_tpu._private.rtconfig import CONFIG

#: Current trace context: (trace_id, span_id) of the innermost open span, or
#: None. Written ONLY while tracing is enabled and the root sampled.
_ctx: ContextVar[Optional[tuple]] = ContextVar("rt_trace_ctx", default=None)

# Cached enabled flag (None = not yet resolved). Resolved lazily and
# re-resolved by refresh() after the cluster config snapshot lands at
# register time, so _system_config={"tracing": True} reaches every process.
_ON: Optional[bool] = None
# Cached head-sampling rate (refresh() re-reads it with _ON): a CONFIG
# attribute read is an os.environ lookup, and _sampled() sits on the
# submit hot path — profiled at ~2.5% of driver throughput uncached.
_RATE: Optional[float] = None

# Bounded per-process span ring (created on first record while enabled).
_ring: Optional[deque] = None
_ring_lock = threading.Lock()
_flusher_kicked = False

_pid = os.getpid()


def enabled() -> bool:
    global _ON
    if _ON is None:
        try:
            _ON = bool(CONFIG.tracing)
        except Exception:
            _ON = False
    return _ON


def refresh() -> None:
    """Re-resolve the enabled flag (called after Worker.connect loads the
    cluster config snapshot) and arm/disarm the rpc frame hook."""
    global _ON, _RATE
    try:
        _ON = bool(CONFIG.tracing)
    except Exception:
        _ON = False
    try:
        _RATE = float(CONFIG.trace_sample)
    except Exception:
        _RATE = 1.0
    from ray_tpu._private import rpc

    rpc.set_trace_hook(on_rpc if _ON else None)
    if not _ON and _ring:
        # A previous session's undrained spans must not leak into a new
        # (untraced) session's controller via the shared flusher.
        _ring.clear()


def _new_id(nbytes: int) -> str:
    return random_id_bytes(nbytes).hex()


def _sampled() -> bool:
    global _RATE
    rate = _RATE
    if rate is None:
        try:
            rate = float(CONFIG.trace_sample)
        except Exception:
            rate = 1.0
        _RATE = rate
    if rate >= 1.0:
        return True
    if rate <= 0.0:
        return False
    return random.random() < rate


def current() -> Optional[tuple]:
    """The live (trace_id, span_id) context, or None."""
    return _ctx.get()


def current_trace_id() -> Optional[str]:
    ctx = _ctx.get()
    return ctx[0] if ctx is not None else None


def _get_ring() -> deque:
    global _ring
    ring = _ring
    if ring is None:
        with _ring_lock:
            if _ring is None:
                _ring = deque(maxlen=max(64, int(CONFIG.trace_buffer_spans)))
            ring = _ring
    return ring


def _label() -> str:
    # Shared with the event plane: one worker-id/pidN resolution (and its
    # pidN-never-cached upgrade subtlety) for span AND event records.
    from ray_tpu._private import events as _events

    return _events.proc_label()


def record_span(trace_id: str, span_id: str, parent: Optional[str],
                name: str, kind: str, start: float, end: float,
                attrs: Optional[dict] = None) -> None:
    """Append one finished span to the process ring. Compact keys — spans
    ride metrics-flush frames at 1 Hz: t/s/p ids, n(ame), k(ind),
    a/b start/end wall time, w(orker), pid, tid (thread lane)."""
    global _flusher_kicked
    sp: dict[str, Any] = {
        "t": trace_id, "s": span_id, "p": parent, "n": name, "k": kind,
        "a": start, "b": end, "w": _label(), "pid": _pid,
        "tid": threading.get_ident() % 1_000_000,
    }
    if attrs:
        sp["at"] = attrs
    _get_ring().append(sp)
    if not _flusher_kicked:
        _flusher_kicked = True
        try:
            from ray_tpu.util import metrics

            metrics.ensure_flusher()
        except Exception:
            pass


def record_span_in(wire_ctx: Optional[tuple], name: str, kind: str,
                   start: float, end: float,
                   attrs: Optional[dict] = None) -> None:
    """Record a span parented to an explicit wire context — for threads that
    carry no contextvar (the llm engine scheduler, the checkpoint writer)."""
    if wire_ctx is None or not enabled():
        return
    record_span(wire_ctx[0], _new_id(8), wire_ctx[1], name, kind, start, end,
                attrs)


def record_instant(wire_ctx: Optional[tuple], name: str, kind: str,
                   attrs: Optional[dict] = None) -> None:
    if wire_ctx is None:
        return
    now = time.time()
    record_span(wire_ctx[0], _new_id(8), wire_ctx[1], name, kind, now, now,
                attrs)


def drain() -> list:
    """Pop all buffered spans (called from the metrics flusher)."""
    from ray_tpu._private import events as _events

    return _events.drain_ring(_ring)


def requeue(spans: list) -> None:
    """Put drained-but-unsent spans back at the FRONT of the ring in their
    original order (the metrics flusher raced a shutdown and could not
    push) so the forced final flush still delivers them. Shares the
    events-plane shed-oldest rebuild (locked: the engine scheduler and
    checkpoint writer record spans from other threads)."""
    from ray_tpu._private import events as _events

    _events.requeue_front(_ring, spans, _ring_lock)


# ------------------------------------------------------------- propagation
def on_submit(name: str, task_id: str = "",
              kind: str = "submit") -> Optional[tuple]:
    """Task/actor-call submit hook (owner side). Inside a traced context the
    submit span chains to it; at top level this IS the root, subject to the
    head-based RT_TRACE_SAMPLE decision. Returns the wire TraceContext
    (trace_id, submit_span_id) to ride the spec, or None (unsampled)."""
    ctx = _ctx.get()
    if ctx is None:
        if not _sampled():
            return None
        trace_id, parent = _new_id(16), None
    else:
        trace_id, parent = ctx
    span_id = _new_id(8)
    now = time.time()
    record_span(trace_id, span_id, parent, name, kind, now, now,
                {"task": task_id} if task_id else None)
    return (trace_id, span_id)


def task_execute_begin(spec) -> Optional[list]:
    """Executor-side: open the execute span and install the trace context so
    everything the task does (nested submits, RPC frames, collectives,
    storage ops) chains under it. Returns an opaque handle for
    task_execute_end, or None when the spec carries no trace."""
    if not enabled():
        return None
    tr = getattr(spec, "trace", None)
    if tr is None:
        return None
    trace_id, parent = tr
    span_id = _new_id(8)
    token = _ctx.set((trace_id, span_id))
    return [trace_id, span_id, parent, spec.name, spec.task_id,
            spec.attempt, time.time(), token]


def task_execute_end(handle: Optional[list], ok: bool = True) -> None:
    if handle is None:
        return
    trace_id, span_id, parent, name, task_id, attempt, start, token = handle
    try:
        _ctx.reset(token)
    except ValueError:
        _ctx.set(None)  # crossed a thread/context boundary; clear instead
    record_span(trace_id, span_id, parent, name, "execute", start,
                time.time(), {"task": task_id, "attempt": attempt, "ok": ok})


def open_root(name: str, kind: str = "op"):
    """Open a root-or-child span WITHOUT installing the contextvar, for
    operations fulfilled on a DIFFERENT thread than the one that opened
    them (the compiled-DAG driver opens `dag.execute` at submit time; its
    collector thread closes it at fulfillment). Returns an opaque handle —
    None when tracing is off or an unsampled root — whose first two slots
    are the wire TraceContext children parent to."""
    if not enabled():
        return None
    ctx = _ctx.get()
    if ctx is None:
        if not _sampled():
            return None
        trace_id, parent = _new_id(16), None
    else:
        trace_id, parent = ctx
    return [trace_id, _new_id(8), parent, name, kind, time.time()]


def close_root(handle, attrs: Optional[dict] = None) -> Optional[str]:
    """Close an open_root handle, recording the span with its real
    duration. Safe from any thread; returns the trace id (None no-op)."""
    if handle is None:
        return None
    trace_id, span_id, parent, name, kind, start = handle
    record_span(trace_id, span_id, parent, name, kind, start, time.time(),
                attrs)
    return trace_id


@contextmanager
def span(name: str, kind: str = "op", attrs: Optional[dict] = None):
    """Span a code block under the current context; no-op when tracing is
    off or the surrounding request was not sampled."""
    if not enabled():
        yield
        return
    ctx = _ctx.get()
    if ctx is None:
        yield
        return
    trace_id, parent = ctx
    span_id = _new_id(8)
    token = _ctx.set((trace_id, span_id))
    start = time.time()
    try:
        yield
    finally:
        try:
            _ctx.reset(token)
        except ValueError:
            _ctx.set((trace_id, parent))
        record_span(trace_id, span_id, parent, name, kind, start, time.time(),
                    attrs)


# ----------------------------------------------------------- serve requests
def start_request(name: str):
    """Root-span hook for ingress (serve HTTP/gRPC proxy). Returns an opaque
    handle; None when tracing is off. An unsampled request still gets a
    timing handle so end_request can apply the RT_TRACE_SLOW_S
    always-sample escalation."""
    if not enabled():
        return None
    if not _sampled():
        return ("unsampled", time.time())
    trace_id, span_id = _new_id(16), _new_id(8)
    token = _ctx.set((trace_id, span_id))
    return (trace_id, span_id, time.time(), token)


def request_trace_id(handle) -> Optional[str]:
    if handle is None or handle[0] == "unsampled":
        return None
    return handle[0]


def end_request(handle, name: str,
                attrs: Optional[dict] = None) -> Optional[str]:
    """Close a request root span. Unsampled requests slower than
    RT_TRACE_SLOW_S escalate to always-sample: they record a (childless)
    root so slow outliers are visible in the trace index even under tight
    head sampling. Returns the trace id when one was recorded."""
    if handle is None:
        return None
    if handle[0] == "unsampled":
        t0 = handle[1]
        end = time.time()
        try:
            slow = float(CONFIG.trace_slow_s)
        except Exception:
            slow = 0.0
        if slow > 0 and end - t0 >= slow:
            trace_id = _new_id(16)
            a = dict(attrs or {})
            a.update(slow=True, sampled=False)
            record_span(trace_id, _new_id(8), None, name, "request", t0, end,
                        a)
            return trace_id
        return None
    trace_id, span_id, t0, token = handle
    try:
        _ctx.reset(token)
    except ValueError:
        _ctx.set(None)
    record_span(trace_id, span_id, None, name, "request", t0, time.time(),
                attrs)
    return trace_id


def escalation_root(st: dict) -> Optional[str]:
    """Always-sample escalation for stall reports: a stalled task whose
    root was NOT sampled still gets a (childless) trace root spanning its
    execution so far, so every `ray-tpu stalls` row links to a timeline.
    `st` is a watchdog executing-task state dict. Returns the minted
    trace id (None when tracing is off)."""
    if not enabled():
        return None
    trace_id = _new_id(16)
    now = time.time()
    # st["started"] is monotonic; recover the wall-clock start.
    started_wall = now - max(0.0, time.monotonic() - st.get("started", 0.0))
    record_span(trace_id, _new_id(8), None,
                str(st.get("name") or "stalled-task"), "stall",
                started_wall, now,
                {"task": st.get("task_id"), "attempt": st.get("attempt"),
                 "stalled": True, "sampled": False})
    return trace_id


# ---------------------------------------------------------------- rpc hook
def on_rpc(event: str, method: str, dur: float = 0.0) -> None:
    """rpc.py trace hook (the PR 9 zero-cost-when-off pattern): frame
    send/recv become instant spans, request round trips ("rpc_call") become
    duration spans + the rt_rpc_frame_seconds histogram — all only inside a
    sampled context, so the unsampled hot path pays one contextvar read."""
    ctx = _ctx.get()
    if ctx is None:
        return
    now = time.time()
    if event == "rpc_call":
        record_span(ctx[0], _new_id(8), ctx[1], f"rpc:{method}", "rpc",
                    now - dur, now)
        m = sys.modules.get("ray_tpu.util.metrics")
        if m is not None:
            try:
                m.RPC_FRAME_SECONDS.observe(dur, tags={"method": method})
            except Exception:
                pass
    else:
        record_span(ctx[0], _new_id(8), ctx[1], f"{event}:{method}", "rpc",
                    now, now)


def default_trace_dir(session_id: str) -> str:
    return os.path.join(CONFIG.session_dir, session_id, "traces")
