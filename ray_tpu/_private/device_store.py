"""Device object plane: actor-resident array objects with tiered transfer.

Parity target: the reference runtime's direct-transport design for GPU
objects (device-resident tensors stay pinned in the producing actor behind
an ObjectRef carrying a device-location hint, and move peer-to-peer over
collective/RDMA transports instead of round-tripping through the plasma
store). This is its TPU-host edition, built on the owner-side refcounting
plumbing: a `jax.Array` produced by a task or actor method is PINNED in the
producing process's DeviceObjectTable instead of being copied to host,
pickled and flushed through the shm store; what crosses the wire is a tiny
placeholder blob whose deserialization resolves through a tier ladder:

  tier 0  same process   the live array, zero-copy (identity-preserving)
  tier 1  same host      the producer exports ONCE into the shm store (the
                         pickle-5 out-of-band buffer view of the device
                         bytes is written straight into the mmap — no
                         payload pickle, no double host copy); consumers
                         attach the segment zero-copy and `device_put`
  tier 2  cross host     export + chunked streamed fetch RPC over the
                         existing object plane, preferring an established
                         collective-group connection to the producer
                         (parallel/collectives, train worker groups) over
                         a fresh TCP connect

Ownership rides the existing refcount machinery: the submitting owner
refcounts the ObjectRef; when the last ref dies the free fans out
controller -> node agents -> producing workers (`device_free`) and the
table entry (plus any shm export) is dropped. Producer death surfaces a
clean ObjectLostError naming the lost producer instead of a hang.

`RT_DEVICE_OBJECTS=0` disables every routing decision in this module, so
all values take today's host-store path byte-for-byte. Values the plane
cannot serve (multi-device/sharded arrays, sub-threshold arrays) fall back
to the host store automatically — warn-once for the sharded case.
"""

from __future__ import annotations

import logging
import pickle
import sys
import threading
import time

from ray_tpu import exceptions as exc
from ray_tpu._private import tracing as _tracing
from ray_tpu._private.rtconfig import CONFIG

logger = logging.getLogger(__name__)


class DeviceObjectTable:
    """Per-process table of produced arrays pinned in (device) memory.

    The pin holds the producer's live `jax.Array` — device buffers included
    — so consumers can read it later without the producer having paid a
    host copy at production time. Entries die on the owner-tracked free
    fan-out (`device_free`) or with the process."""

    __slots__ = ("_lock", "_entries", "_bytes")

    def __init__(self):
        self._lock = threading.Lock()
        self._entries: dict[str, dict] = {}  # oid -> {"array","nbytes","exported"}
        self._bytes = 0

    def pin(self, oid: str, array, nbytes: int) -> None:
        with self._lock:
            if oid in self._entries:
                return
            self._entries[oid] = {"array": array, "nbytes": nbytes}
            self._bytes += nbytes

    def get(self, oid: str):
        with self._lock:
            ent = self._entries.get(oid)
            return None if ent is None else ent["array"]

    def holds(self, oid: str) -> bool:
        with self._lock:
            return oid in self._entries

    def discard(self, oid: str) -> bool:
        """Drop a pin. Returns True if an entry existed."""
        with self._lock:
            ent = self._entries.pop(oid, None)
            if ent is None:
                return False
            self._bytes -= ent["nbytes"]
            return True

    def clear(self) -> None:
        with self._lock:
            self._entries.clear()
            self._bytes = 0

    def stats(self) -> dict:
        with self._lock:
            return {"count": len(self._entries), "bytes": self._bytes}


_TABLE = DeviceObjectTable()
_warned: set[str] = set()
_conn_lock = threading.Lock()
_conns: dict[tuple, object] = {}  # producer addr -> cached rpc.Connection
# Resolve-tier counters (GIL-atomic int bumps, no lock): how this
# process's placeholder resolutions landed. The llm_pipeline_decode bench
# gate reads these off every stage actor to PROVE the zero-RPC steady
# state — `export_rpc` and `fetch` must stay at 0 when producers export
# eagerly at publish time (dag._EdgePublisher).
_RESOLVE_STATS = {"tier0": 0, "store_hit": 0, "export_rpc": 0, "fetch": 0,
                  "edge_pins": 0}


def resolve_stats() -> dict:
    return dict(_RESOLVE_STATS)


def reset_resolve_stats() -> None:
    for k in _RESOLVE_STATS:
        _RESOLVE_STATS[k] = 0
# Fired (from any thread) after every pin/discard/clear so the hosting
# process can report 0<->nonzero residency transitions (worker_proc tells
# its node agent, which exempts pinned pool workers from the idle reap).
_pins_listener = None


def set_pins_listener(cb) -> None:
    global _pins_listener
    _pins_listener = cb


def _notify_pins() -> None:
    cb = _pins_listener
    if cb is not None:
        try:
            cb()
        except Exception:
            pass


def table() -> DeviceObjectTable:
    return _TABLE


def table_stats() -> dict:
    return _TABLE.stats()


def _warn_once(key: str, msg: str) -> None:
    if key in _warned:
        return
    _warned.add(key)
    logger.warning(msg)


# ------------------------------------------------------------- eligibility
def eligible(value, min_bytes: "int | None" = None) -> bool:
    """True iff `value` should ride the device plane: a live, single-device,
    fully-addressable jax.Array at or above the size threshold, with the
    plane enabled. Cheap for non-array values (one sys.modules probe + one
    isinstance) — this runs on every task/actor return. `min_bytes`
    overrides the general plane's RT_DEVICE_OBJECT_MIN_BYTES threshold
    (compiled-DAG edges pass RT_DAG_EDGE_MIN_BYTES: pre-negotiated
    point-to-point edges amortize the pin on much smaller arrays)."""
    jax = sys.modules.get("jax")
    if jax is None:
        # No jax imported in this process => the value can't be a jax.Array.
        return False
    try:
        if not isinstance(value, jax.Array):
            return False
    except Exception:
        return False
    if not CONFIG.device_objects:
        return False
    try:
        nbytes = int(value.nbytes)
        if nbytes < (CONFIG.device_object_min_bytes
                     if min_bytes is None else min_bytes):
            return False
        if value.is_deleted():
            return False
        if not value.is_fully_addressable or len(value.sharding.device_set) != 1:
            _warn_once(
                "sharded",
                "device object plane: multi-device/sharded jax.Array falls "
                "back to the host store (the plane serves single-device "
                "arrays; shard_map outputs gather through the host path)")
            return False
    except Exception:
        return False
    return True


# ------------------------------------------------------------ wire format
class _DeviceRef:
    """Placeholder that rides the wire in place of the array payload.
    Unpickling it IN ANY PROCESS resolves through the tier ladder — so the
    hint flows through every existing path (direct replies, inline
    advertises, task args, borrowed refs) without new unpickler hooks."""

    __slots__ = ("desc",)

    def __init__(self, desc: dict):
        self.desc = desc

    def __reduce__(self):
        return (_resolve, (self.desc,))


class _ExportWrap:
    """Wrapper for the shm EXPORT blob: deserializing the export in any
    consumer rebuilds a jax.Array (device_put over the zero-copy shm view),
    so a consumer that finds the exported segment directly (same-host
    sibling, post-fetch read) gets the same type the placeholder path
    yields."""

    __slots__ = ("nd",)

    def __init__(self, nd):
        self.nd = nd

    def __reduce__(self):
        return (_rebuild_export, (self.nd,))


def _rebuild_export(nd):
    import jax

    return jax.device_put(nd)


def _ref_blob(desc: dict) -> bytes:
    """The placeholder in the standard inline wire layout so every
    existing blob consumer (fast-path deserialize included) handles it
    untouched."""
    from ray_tpu._private.serialization import inline_header_blob

    return inline_header_blob(pickle.dumps(_DeviceRef(desc), protocol=5))


def _make_desc(oid: str, value, nbytes: int, worker) -> dict:
    return {
        "oid": oid,
        "nbytes": nbytes,
        "shape": tuple(value.shape),
        "dtype": str(value.dtype),
        "worker": worker.worker_id,
        "addr": tuple(worker.server_addr),
        "node": worker.node_id,
    }


def pin_return(oid: str, value, worker) -> tuple:
    """Producer side of a task/actor return: pin the live array and emit
    the standard result tuple (oid, inline, size, holder) with the
    placeholder as the inline payload and this worker's RPC address as the
    device-location hint."""
    nbytes = int(value.nbytes)
    _TABLE.pin(oid, value, nbytes)
    _ensure_metrics_flusher()
    _notify_pins()
    blob = _ref_blob(_make_desc(oid, value, nbytes, worker))
    return (oid, [blob], nbytes, tuple(worker.server_addr))


def pin_put(oid: str, value, worker) -> tuple[bytes, int]:
    """Producer side of an owner-local put()/large-arg promotion: pin and
    return (placeholder_blob, nbytes)."""
    nbytes = int(value.nbytes)
    _TABLE.pin(oid, value, nbytes)
    _ensure_metrics_flusher()
    _notify_pins()
    return _ref_blob(_make_desc(oid, value, nbytes, worker)), nbytes


def pin_edge(oid: str, value, worker):
    """Pin a produced array for a PRE-NEGOTIATED point-to-point edge
    (compiled-DAG device edges, README "Compiled graphs"): like pin_return
    but OUTSIDE the owner-refcount plane — no controller registration, no
    free fan-out. The producing stage owns the pin's lifetime and drops it
    via free_local once every consumer's channel read has provably
    advanced past the invocation (the edge protocol's retention window).
    Returns the placeholder object whose pickle is the ~200B wire payload
    and whose unpickle resolves through the ordinary tier ladder."""
    nbytes = int(value.nbytes)
    _TABLE.pin(oid, value, nbytes)
    _RESOLVE_STATS["edge_pins"] += 1
    _ensure_metrics_flusher()
    _notify_pins()
    return _DeviceRef(_make_desc(oid, value, nbytes, worker))


def advert_fields(worker_id: str, node_id: str) -> dict:
    """Extra register_put fields marking a directory entry device-resident
    (consumed by the controller for list_objects' plane column, free
    fan-out routing, and the producer-death lost sweep)."""
    return {"plane": "device", "device_worker": worker_id,
            "device_node": node_id}


def holds(oid: str) -> bool:
    return _TABLE.holds(oid)


def has_pins() -> bool:
    """Lock-free emptiness probe for hot paths (a stale read just defers
    the drop to the fan-out path, which is idempotent)."""
    return bool(_TABLE._entries)


# ------------------------------------------------------------------ frees
def free_local(oids, store=None) -> int:
    """Drop pins (and this process's shm export mappings) for oids produced
    here — the terminal hop of the owner-tracked free fan-out
    (controller -> node agent -> `device_free` push -> this). Returns the
    number of entries dropped."""
    n = 0
    for oid in oids:
        if _TABLE.discard(oid):
            n += 1
            if store is not None:
                try:
                    store.delete(oid)  # export segment, if one was made
                except Exception:
                    pass
    if n:
        _notify_pins()
    return n


def on_worker_shutdown() -> None:
    """Session teardown: drop every pin and forget peer connections (they
    ride the dying IO loop); reset the metrics drain cache so the next
    session's gauges report from scratch."""
    _TABLE.clear()
    with _conn_lock:
        _conns.clear()
    try:
        from ray_tpu.util import metrics

        metrics.reset_device_stats_cache()
    except Exception:
        pass


# -------------------------------------------------------------- producer
def host_view(arr):
    """Host ndarray for a (single-device or shard) jax array: a ZERO-COPY
    view of the array's host memory on CPU/TPU-host backends, a D2H copy
    elsewhere. jax arrays are immutable, so sharing the view is safe for
    readers that outlive the call (the export path below) as long as they
    hold a reference — EXCEPT under XLA buffer donation, which frees the
    memory behind the view; long-lived readers must copy views that don't
    own their data (see checkpoint._snapshot_leaf)."""
    import numpy as np

    return np.asarray(arr)


def export_to_store(oid: str, store) -> bool:
    """Materialize a pinned array's bytes into the local shm store (the
    same-host / cross-host serving copy). The export blob deserializes to a
    jax.Array (see _ExportWrap); its out-of-band buffer — on CPU/TPU-host
    backends a zero-copy view of the array's host memory — is written
    straight into the destination mmap by put_serialized: ONE host copy
    total, no pickle of the payload. Idempotent; returns False if the oid
    is neither pinned nor already exported."""
    from ray_tpu._private.serialization import serialize

    arr = _TABLE.get(oid)
    if arr is None:
        return store.contains(oid)
    if store.contains(oid):
        return True  # repeat consumers attach the existing export for free
    nd = host_view(arr)  # zero-copy view on host backends
    sobj = serialize(_ExportWrap(nd))
    store.put_serialized(oid, sobj)
    return True


# -------------------------------------------------------------- consumer
_tls = threading.local()


def set_resolve_deadline(deadline) -> None:
    """Propagate a get(timeout=...) deadline into placeholder resolution on
    this thread (set around deserialization by Worker._materialize, cleared
    with None): the tier ladder does real network work inside unpickling,
    which must not outlive the caller's timeout. No deadline = the ladder's
    own defaults."""
    _tls.deadline = deadline


def _op_timeout(default: float) -> float:
    d = getattr(_tls, "deadline", None)
    if d is None:
        return default
    rem = d - time.monotonic()
    if rem <= 0:
        raise exc.GetTimeoutError("get() timed out resolving device object")
    return min(default, rem)


def _resolve(desc: dict):
    """Tier-ladder resolution; the unpickle target of _DeviceRef."""
    oid = desc["oid"]
    arr = _TABLE.get(oid)
    if arr is not None:
        _RESOLVE_STATS["tier0"] += 1
        return arr  # tier 0: same process, zero-copy, identity-preserving
    from ray_tpu._private.worker import global_worker

    w = global_worker()
    if w is None:
        raise exc.ObjectLostError(
            f"device object {oid[:16]} cannot be resolved: no ray_tpu "
            f"runtime in this process (producer {desc['worker'][:12]})")
    mv = w.store.get(oid)  # a prior resolve / sibling export already local?
    if mv is not None:
        _RESOLVE_STATS["store_hit"] += 1
    else:
        # Tiers 1/2 do real network work (producer export RPC + attach or
        # chunked fetch): span it so a traced consumer's timeline shows
        # where device-object localization time goes. Tier 0 above stays
        # span-free — a zero-copy dict hit must not pay tracing overhead.
        same_host = tuple(desc["addr"])[0] == w.server_addr[0]
        with _tracing.span("device.resolve", "device",
                           {"oid": oid[:16], "nbytes": desc.get("nbytes"),
                            "tier": "same_host" if same_host
                            else "cross_host"}):
            mv = _localize(w, desc)
    return w._deserialize_blob(mv)


def _localize(w, desc: dict):
    """Move the bytes within reach: ask the producer to export, then attach
    (same host) or pull over the streamed fetch RPC (cross host). All
    failures collapse into ObjectLostError naming the lost producer — a
    consumer must never hang on a dead producer."""
    oid = desc["oid"]
    addr = tuple(desc["addr"])
    try:
        conn = _peer_conn(w, addr)
        t = _op_timeout(60)
        rep = w.io.run(conn.call("export_device_object", oid=oid,
                                 _timeout=t), timeout=t + 5)
        if not rep.get("found"):
            raise exc.ObjectLostError(
                f"device object {oid[:16]} lost: producing worker "
                f"{desc['worker'][:12]} no longer holds it (freed or "
                f"restarted)")
        if addr[0] == w.server_addr[0]:
            mv = w.store.get(oid)  # tier 1: same host, attach the export
            if mv is not None:
                _RESOLVE_STATS["export_rpc"] += 1
                return mv
        if _fetch_via_conn(w, conn, oid,
                           timeout=_op_timeout(120.0)):  # tier 2: pull
            mv = w.store.get(oid)
            if mv is not None:
                _RESOLVE_STATS["fetch"] += 1
                return mv
        raise exc.ObjectLostError(
            f"device object {oid[:16]} lost: fetch from producer "
            f"{desc['worker'][:12]} at {addr} returned nothing")
    except (exc.ObjectLostError, exc.GetTimeoutError):
        raise
    except Exception as e:
        raise exc.ObjectLostError(
            f"device object {oid[:16]} lost: producing worker "
            f"{desc['worker'][:12]} at {addr[0]}:{addr[1]} is unreachable "
            f"({type(e).__name__}: {e})") from e


def _peer_conn(w, addr: tuple):
    """Connection to the producer, preferring (in order) an established
    collective-group link to that address — producer and consumer sitting
    in the same group (parallel/collectives, train worker groups) ride the
    group's transport instead of opening a new socket — then a cached
    direct connection, then a fresh connect."""
    conn = _collective_conn(addr)
    if conn is not None:
        return conn
    with _conn_lock:
        conn = _conns.get(addr)
    if conn is not None and not conn.closed:
        return conn
    from ray_tpu._private import rpc

    t = _op_timeout(10)
    conn = w.io.run(rpc.connect(*addr, timeout=t), timeout=t + 5)
    with _conn_lock:
        _conns[addr] = conn
    return conn


def _collective_conn(addr: tuple):
    col = sys.modules.get("ray_tpu.util.collective")
    if col is None:
        return None
    try:
        for g in col._manager._groups.values():
            for rank, a in g.addrs.items():
                if tuple(a) == addr:
                    conn = g.conns.get(rank)
                    if conn is not None and not conn.closed:
                        return conn
    except Exception:
        pass
    return None


def _fetch_via_conn(w, conn, oid: str, timeout: float = 120.0) -> bool:
    """Chunked pull of the exported blob into the local store over an
    existing connection (the fetch_object server side is the same one the
    host object plane serves)."""
    import asyncio

    chunk = CONFIG.object_chunk_bytes

    async def _go():
        rep = await conn.call("fetch_object", oid=oid, offset=0, length=chunk)
        if not rep.get("found"):
            return False
        size = rep["size"]
        data = rep["data"]
        if size <= len(data):
            w.store.put(oid, [data])
            return True
        stream = w.store.begin_stream(oid, size)
        if stream is None:
            return True  # raced: a local copy already exists
        try:
            woff = 0
            while True:
                await asyncio.to_thread(stream.write, woff, data)
                woff += len(data)
                if woff >= size:
                    break
                rep = await conn.call("fetch_object", oid=oid, offset=woff,
                                      length=chunk)
                if not rep.get("found"):
                    return False  # producer dropped it mid-stream
                data = rep["data"]
            sealed = stream.seal()
            stream = None
            return sealed or w.store.contains(oid)
        finally:
            if stream is not None:
                stream.abort()

    return bool(w.io.run(_go(), timeout=timeout))


# ------------------------------------------------------------ observability
_metrics_hooked = False


def _ensure_metrics_flusher() -> None:
    """First pin starts the metrics flusher so the rt_device_objects gauges
    report even in processes that never mint another metric."""
    global _metrics_hooked
    if _metrics_hooked:
        return
    _metrics_hooked = True
    try:
        from ray_tpu.util import metrics

        metrics.ensure_flusher()
    except Exception:
        pass
